"""Per-request sampling (ISSUE 13): params, seeded purity, distribution.

The decisive properties:

* SEEDED PURITY — a request's token stream is a pure function of its
  ``(prompt, SamplingParams)``: identical across ``decode_ahead``
  {1, 4, 8}, dense vs paged layouts, an engine restart, and a replay on
  a speculative engine at fixed config.  Position-keyed PRNG
  (``fold_in(base_key, n)`` for the token at generated index ``n``) is
  what buys this — the host's windowing never touches the key schedule.
* GREEDY LIMIT — ``temperature == 0`` requests are token-identical to
  the engine's greedy output across layouts × decode_ahead ×
  ±speculative: sampling rows ride the SAME program, selected by data.
* ONE PROGRAM FAMILY — after prewarm, serving any mix of per-request
  ``(temperature, top_p, top_k, seed)`` configs compiles ZERO new
  programs (top-k rides a per-slot int32 data plane — ISSUE 14).
* DISTRIBUTION — the speculative verify's rejection sampling (accept a
  draft with prob ``p_target(d)``, resample the masked residual on
  reject) emits the target sampling distribution exactly; chi-squared
  gated over >= 10k draws on a small vocab, for both a high-probability
  and an adversarial (least-likely) draft.
* EXACTLY-ONCE — a chaos-killed replica's sampled requests replay
  token-identical on a survivor with exactly-once streaming delivery.
* STATS — sampled-request accounting (counts, mean temperature, NLL
  histogram) flows through ``ServingStats`` and the router rollup.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
    _tempered_rows,
    _verify_sample_core,
    make_decode_step,
    make_prefill,
)
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    Router,
    SamplingParams,
    ServingStats,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import base_key
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import CompileTracker

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)

PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6], [9, 1, 7]]

# chi-squared 99.9th-percentile critical values by dof (no scipy in the
# image; a fixed table keeps the gate dependency-free)
CHI2_999 = {1: 10.83, 2: 13.82, 3: 16.27, 4: 18.47, 5: 20.52,
            6: 22.46, 7: 24.32}


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("buckets", (8,))
    return InferenceEngine(model, params, **kw)


def _serve(model, params, sampling=None, prompts=PROMPTS, max_new=8, **kw):
    """Serve the wave; returns (token lists, logprob lists).  ``sampling``
    is one SamplingParams for every request or a per-request list."""
    eng = _engine(model, params, **kw)
    if not isinstance(sampling, (list, tuple)):
        sampling = [sampling] * len(prompts)
    reqs = [eng.submit(np.asarray(p, np.int32), max_new=max_new, sampling=s)
            for p, s in zip(prompts, sampling)]
    eng.run()
    eng.close()
    assert all(r.status == "done" for r in reqs)
    return ([list(r.generated) for r in reqs],
            [list(r.logprobs) for r in reqs])


# ----------------------------------------------------------------------
# SamplingParams: validation at submit, key derivation


def test_sampling_params_validation_and_key():
    assert not SamplingParams().sampled              # greedy default
    assert SamplingParams(temperature=0.7).sampled
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-0.1)
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=float("nan"))
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(temperature=1.0, top_p=-0.2)
    # top_p filters a sampling distribution: meaningless at temperature 0
    with pytest.raises(ValueError, match="temperature > 0"):
        SamplingParams(temperature=0.0, top_p=0.9)
    with pytest.raises(ValueError, match="seed"):
        SamplingParams(temperature=1.0, seed=-1)
    with pytest.raises(ValueError, match="seed"):
        SamplingParams(temperature=1.0, seed=1 << 64)
    with pytest.raises(ValueError, match="seed"):
        SamplingParams(temperature=1.0, seed=True)

    # the base key IS jax.random.PRNGKey(seed)'s raw data — host-derived
    # (no device dispatch at submit) — for 32-bit seeds; past 32 bits the
    # host derivation keeps the high word PRNGKey silently truncates
    # under the default x64-disabled config, so distinct seeds stay
    # distinct keys across the whole documented [0, 2^64) range
    for s in (0, 5, (1 << 31) + 9):
        np.testing.assert_array_equal(
            base_key(s), np.asarray(jax.random.key_data(
                jax.random.PRNGKey(s)), np.uint32).reshape(-1)[-2:])
    for s in ((1 << 32) + 7, (1 << 63) + 3):
        np.testing.assert_array_equal(
            base_key(s),
            np.asarray([(s >> 32) & 0xFFFFFFFF, s & 0xFFFFFFFF], np.uint32))
    np.testing.assert_array_equal(
        SamplingParams(temperature=1.0, seed=5).key(), base_key(5))


def test_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        SamplingParams(temperature=1.0, top_k=-1)
    with pytest.raises(ValueError, match="top_k"):
        SamplingParams(temperature=1.0, top_k=True)
    with pytest.raises(ValueError, match="top_k"):
        SamplingParams(temperature=1.0, top_k=2.5)
    # top_k filters a sampling distribution: meaningless at temperature 0
    with pytest.raises(ValueError, match="temperature > 0"):
        SamplingParams(temperature=0.0, top_k=3)
    assert SamplingParams(temperature=1.0, top_k=5).top_k == 5


def test_filter_topk_rows_per_row_support():
    """The data-plane top-k filter (ISSUE 14): each ROW keeps its own k
    highest logits and floors the rest; k=0 and k>=vocab are per-row
    no-ops (the off states), all in one (B, V) program."""
    from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
        _filter_topk_rows,
    )
    rng = np.random.default_rng(0)
    raw = rng.normal(size=(4, 16)).astype(np.float32)  # no ties w.h.p.
    ks = jnp.asarray([0, 1, 3, 16], jnp.int32)
    out = np.asarray(_filter_topk_rows(jnp.asarray(raw), ks))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0], raw[0])      # 0 = filter off
    np.testing.assert_array_equal(out[3], raw[3])      # k >= vocab = off
    for row, k in ((1, 1), (2, 3)):
        keep = np.zeros(16, bool)
        keep[np.argsort(raw[row])[-k:]] = True
        np.testing.assert_array_equal(out[row][keep], raw[row][keep])
        assert (out[row][~keep] == neg).all(), (row, k)


def test_top_k_one_is_argmax_and_vocab_k_is_noop():
    """``top_k=1`` at ANY temperature is argmax — token-identical to the
    greedy engine (seed inert in effect); ``top_k >= vocab`` leaves the
    distribution untouched — stream-identical to the same seed without
    the filter.  Both ride the same compiled window as every other row."""
    model, params = _model_and_params(seed=8)
    want, _ = _serve(model, params)                   # greedy reference
    got, _ = _serve(model, params,
                    sampling=SamplingParams(temperature=1.7, top_k=1,
                                            seed=99))
    assert got == want
    v = KW["num_classes"]
    base, _ = _serve(model, params,
                     sampling=SamplingParams(temperature=0.9, seed=5))
    full, _ = _serve(model, params,
                     sampling=SamplingParams(temperature=0.9, top_k=v,
                                             seed=5))
    assert base == full


def test_min_p_validation():
    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(temperature=1.0, min_p=-0.1)
    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(temperature=1.0, min_p=1.5)
    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(temperature=1.0, min_p=float("nan"))
    # min_p filters a sampling distribution: meaningless at temperature 0
    with pytest.raises(ValueError, match="temperature > 0"):
        SamplingParams(temperature=0.0, min_p=0.5)
    assert SamplingParams(temperature=1.0, min_p=0.25).min_p == 0.25


def test_filter_minp_rows_per_row_support():
    """The data-plane min-p filter (ISSUE 16 satellite): each ROW cuts
    tokens whose probability is below its own ``min_p * max_prob`` —
    the threshold scales with the row's confidence; min_p=0 is a per-row
    no-op and min_p=1 keeps only the argmax, all in one (B, V) program."""
    from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
        _filter_minp_rows,
    )
    rng = np.random.default_rng(1)
    raw = rng.normal(size=(3, 16)).astype(np.float32)  # no ties w.h.p.
    mps = jnp.asarray([0.0, 0.5, 1.0], jnp.float32)
    out = np.asarray(_filter_minp_rows(jnp.asarray(raw), mps))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0], raw[0])      # 0 = filter off
    probs = np.exp(raw[1]) / np.exp(raw[1]).sum()
    keep = probs >= 0.5 * probs.max()
    np.testing.assert_array_equal(out[1][keep], raw[1][keep])
    assert (out[1][~keep] == neg).all()
    top = np.argmax(raw[2])                            # 1 = argmax only
    assert out[2][top] == raw[2][top]
    mask = np.ones(16, bool)
    mask[top] = False
    assert (out[2][mask] == neg).all()


def test_min_p_one_is_argmax_and_zero_is_noop():
    """``min_p=1.0`` at ANY temperature keeps only the argmax — token-
    identical to the greedy engine (seed inert in effect); ``min_p=0``
    leaves the distribution untouched — stream-identical to the same
    seed without the filter.  Same compiled window as every other row."""
    model, params = _model_and_params(seed=9)
    want, _ = _serve(model, params)                   # greedy reference
    got, _ = _serve(model, params,
                    sampling=SamplingParams(temperature=1.5, min_p=1.0,
                                            seed=77))
    assert got == want
    base, _ = _serve(model, params,
                     sampling=SamplingParams(temperature=0.9, seed=5))
    off, _ = _serve(model, params,
                    sampling=SamplingParams(temperature=0.9, min_p=0.0,
                                            seed=5))
    assert base == off


def test_scheduler_submit_rejects_non_params():
    sched = FIFOScheduler(max_len=32, buckets=(8,))
    with pytest.raises(ValueError, match="SamplingParams"):
        sched.submit([1, 2], max_new=4, sampling=(0.7, 0.9))
    # a validated instance passes through onto the Request
    req = sched.submit([1, 2], max_new=4,
                       sampling=SamplingParams(temperature=0.7, seed=3))
    assert req.sampling.seed == 3 and req.logprobs == []


# ----------------------------------------------------------------------
# greedy limit: temperature == 0 rows == the greedy engine, everywhere


def test_greedy_limit_matches_engine_greedy_everywhere():
    model, params = _model_and_params(seed=1)
    want, _ = _serve(model, params)                  # engine-default greedy
    zero = SamplingParams(temperature=0.0, seed=123)  # seed must be inert
    for kw in ({}, {"decode_ahead": 4}, {"kv_page_size": 8},
               {"speculative": "ngram", "draft_len": 3},
               {"speculative": "ngram", "draft_len": 3, "decode_ahead": 4}):
        got, logps = _serve(model, params, sampling=zero, **kw)
        assert got == want, kw
        assert all(len(lp) == len(t) for lp, t in zip(logps, got))


def test_logprobs_are_raw_logits_log_softmax():
    """Every generated token carries log_softmax(RAW logits)[token] — the
    model's pre-temperature distribution.  Pinned against a reference
    prefill for the first token, greedy and sampled alike."""
    model, params = _model_and_params(seed=2)
    prompt = np.asarray([9, 4, 2], np.int32)
    padded = np.zeros((1, 8), np.int32)
    padded[0, :3] = prompt
    _, last = make_prefill(model, 48)(
        params, jnp.asarray(padded), jnp.asarray([3], np.int32))
    ref = np.asarray(jax.nn.log_softmax(last, axis=-1))[0]

    for sp in (None, SamplingParams(temperature=1.1, top_p=0.9, seed=7)):
        toks, logps = _serve(model, params, sampling=sp,
                             prompts=[prompt], max_new=4)
        assert len(logps[0]) == len(toks[0]) == 4
        assert logps[0][0] == pytest.approx(float(ref[toks[0][0]]), abs=1e-5)
        assert all(lp <= 1e-6 for lp in logps[0])   # log-probs, not probs


# ----------------------------------------------------------------------
# seeded purity: the stream is a function of the seed, not the batching


def test_seeded_stream_invariant_across_k_layout_restart():
    model, params = _model_and_params(seed=3)
    sp = SamplingParams(temperature=0.8, top_p=0.9, seed=1234)
    want, want_lp = _serve(model, params, sampling=sp)  # decode_ahead=1
    for kw in ({"decode_ahead": 4}, {"decode_ahead": 8},
               {"kv_page_size": 8}, {}):               # {} = restart
        got, lp = _serve(model, params, sampling=sp, **kw)
        assert got == want, kw
        for a, b in zip(lp, want_lp):
            np.testing.assert_allclose(a, b, atol=1e-5, err_msg=str(kw))
    # a different seed is a different stream (vocab 16, 8 tokens, 4 reqs:
    # a full collision would be astronomically unlucky)
    other, _ = _serve(model, params,
                      sampling=SamplingParams(temperature=0.8, top_p=0.9,
                                              seed=4321))
    assert other != want


def test_spec_sampled_replay_token_identical():
    """At fixed engine config a speculative sampled serve replays
    token-identically (same seeds -> same accepts -> same residuals).
    The spec and plain sample PATHS differ by design — only their
    distributions and the greedy limit coincide."""
    model, params = _model_and_params(seed=4)
    mix = [SamplingParams(temperature=0.9, seed=i) for i in range(3)]
    mix.append(None)                                  # greedy rider
    kw = dict(speculative="ngram", draft_len=3)
    a, a_lp = _serve(model, params, sampling=mix, **kw)
    b, b_lp = _serve(model, params, sampling=mix, **kw)
    assert a == b and a_lp == b_lp
    # the greedy rider matches the all-greedy reference in the same batch
    want, _ = _serve(model, params)
    assert a[3] == want[3]


# ----------------------------------------------------------------------
# one program family: sampling configs are data, never shapes


def test_zero_new_programs_across_sampling_configs():
    model, params = _model_and_params(seed=5)
    mixes = [None, SamplingParams(temperature=0.7, top_p=0.9, seed=1),
             SamplingParams(temperature=1.3, top_k=4, seed=9),
             SamplingParams(temperature=0.4, top_p=0.3, top_k=7, seed=42),
             SamplingParams(temperature=0.9, min_p=0.2, seed=17)]
    for kw in ({"decode_ahead": 4},
               {"speculative": "ngram", "draft_len": 3}):
        eng = _engine(model, params, **kw)
        eng.prewarm()
        before = eng._compile.snapshot()
        reqs = [eng.submit(np.asarray(p, np.int32), max_new=8, sampling=s)
                for p, s in zip(PROMPTS, mixes)]
        eng.run()
        d = CompileTracker.delta(eng._compile.snapshot(), before)
        assert d["n_compiled_programs"] == 0, (kw, d)
        assert all(r.status == "done" for r in reqs)
        eng.close()


# ----------------------------------------------------------------------
# distribution: rejection sampling == target sampling, chi-squared gated


def _chi2_gate(counts, p, label):
    """Pearson chi-squared at the 99.9th percentile, merging categories
    with expected count < 5 (the classical validity floor) into one bin."""
    n = counts.sum()
    # a token outside the target's support (nucleus-filtered out) must
    # never be emitted at all — that's a correctness bug, not noise
    assert counts[p == 0].sum() == 0, f"{label}: emitted zero-prob token"
    counts, p = counts[p > 0], p[p > 0]
    exp = n * p
    small = exp < 5.0
    if small.any():
        counts = np.concatenate([counts[~small], [counts[small].sum()]])
        exp = np.concatenate([exp[~small], [exp[small].sum()]])
    assert exp.min() >= 1.0, f"{label}: degenerate target distribution"
    chi2 = float((((counts - exp) ** 2) / exp).sum())
    dof = len(counts) - 1
    assert chi2 < CHI2_999[dof], (
        f"{label}: chi2 {chi2:.2f} >= {CHI2_999[dof]} (dof {dof}) over "
        f"{int(n)} draws — emitted distribution != target")


def test_verify_rejection_sampling_matches_target_distribution():
    """>= 10k draws through the speculative verify on a vocab-8 model:
    the first emitted token's empirical distribution must match the
    tempered/nucleus target — whether the draft is the mode (mostly
    accepted) or the least likely token (mostly rejected -> residual)."""
    model, params = _model_and_params(seed=6, num_classes=8)
    B, reps, max_len = 512, 20, 16
    prompt = np.tile(np.asarray([[3, 5, 1, 6]], np.int32), (B, 1))
    prefill = make_prefill(model, max_len)
    cache0, last = prefill(params, jnp.asarray(prompt))
    pend = jnp.argmax(last, -1).astype(jnp.int32)     # pending first token
    # reference logits at the position the verify's lane 0 samples
    _, logits0 = make_decode_step(model, max_len)(params, cache0, pend)
    verify = jax.jit(functools.partial(
        _verify_sample_core, model, max_len=max_len, pad_id=0))

    for temp, topp, pick, label in ((1.2, 0.0, "hi", "plain/mode-draft"),
                                    (0.9, 0.85, "lo", "nucleus/worst-draft")):
        temps = jnp.full((B,), temp, jnp.float32)
        topps = jnp.full((B,), topp, jnp.float32)
        p = np.asarray(jax.nn.softmax(
            _tempered_rows(logits0[:1], temps[:1], topps[:1],
                           jnp.zeros((1,), jnp.int32),
                           jnp.zeros((1,), jnp.float32))))[0]
        draft = int(np.argmax(p) if pick == "hi" else np.argmin(p))
        chunk = np.zeros((B, 2), np.int32)
        chunk[:, 0] = np.asarray(pend)
        chunk[:, 1] = draft
        counts = np.zeros(p.size)
        for rep in range(reps):
            seeds = range(rep * B, (rep + 1) * B)
            keys = jnp.asarray(np.stack([base_key(s) for s in seeds]))
            _, toks, logps, acc, _ = verify(
                params, cache0, jnp.asarray(chunk),
                jnp.ones((B,), jnp.int32), jnp.ones((B,), bool),
                temps, topps, jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.float32), keys,
                jnp.zeros((B,), jnp.int32))
            np.add.at(counts, np.asarray(toks)[:, 0], 1)
        assert counts.sum() == B * reps >= 10_000
        _chi2_gate(counts, p, label)


# ----------------------------------------------------------------------
# failover: seeded replay is token-identical with exactly-once streaming


def test_router_failover_replays_sampled_exactly_once():
    """Chaos kills one replica mid-wave; its sampled collateral re-decodes
    on a survivor.  Seeded purity makes the replay token-identical, and
    the delivered high-water mark suppresses the replayed prefix — each
    stream sees every token exactly once."""
    model, params = _model_and_params(seed=7)
    mix = [SamplingParams(temperature=0.9, top_p=0.9, seed=i * 7 + 1)
           for i in range(len(PROMPTS) - 1)] + [None]

    def factory(tid, chaos=None):
        return InferenceEngine(
            model, params, slots=2, max_len=16,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=16),
            trace_tid=tid, chaos=chaos, stall_timeout_s=None)

    # fault-free reference: one engine, same sampling
    eng = factory(0)
    want = [eng.submit(np.asarray(p, np.int32), max_new=6, sampling=s)
            for p, s in zip(PROMPTS, mix)]
    eng.run()
    eng.close()
    want_toks = [list(r.generated) for r in want]

    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-step", kind="transient", at=(1,)),)))
    streams: dict[int, list[int]] = {}
    r = Router(lambda tid: factory(tid, chaos=inj), 2)
    rrs = [r.submit(p, max_new=6, sampling=s,
                    callback=lambda rr, tok: streams.setdefault(
                        rr.id, []).append(int(tok)))
           for p, s in zip(PROMPTS, mix)]
    r.run_until_done()
    assert [list(rr.generated) for rr in rrs] == want_toks
    assert all(rr.status == "done" for rr in rrs)
    assert r.failovers == 1
    moved = [rr for rr in rrs if rr.redispatches]
    assert moved                                     # someone was displaced
    for rr in rrs:                                   # exactly-once delivery
        assert streams.get(rr.id, []) == list(rr.generated)
        assert len(rr.logprobs) == len(rr.generated)
    # the rollup carries the sampled-traffic accounting (attempts of the
    # displaced sampled requests count too — they are engine records)
    summ = r.summary()
    assert summ["n_sampled_requests"] >= len(PROMPTS) - 1
    assert summ["mean_temperature"] == pytest.approx(0.9, abs=1e-4)
    assert summ["logprob_tokens"] > 0 and summ["nll_p50"] is not None
    r.close()


# ----------------------------------------------------------------------
# stats: schema stays stable, ratios null-not-NaN


def test_stats_sampling_fields_and_merge():
    model, params = _model_and_params(seed=8)
    eng = _engine(model, params)
    sp = SamplingParams(temperature=0.6, seed=11)
    reqs = [eng.submit(np.asarray(p, np.int32), max_new=5, sampling=s)
            for p, s in zip(PROMPTS[:2], (sp, None))]
    eng.run()
    s = eng.stats.summary()
    assert s["n_sampled_requests"] == 1
    assert s["mean_temperature"] == pytest.approx(0.6, abs=1e-4)
    assert s["logprob_tokens"] == sum(len(r.generated) for r in reqs)
    assert s["nll_p50"] is not None and s["nll_p50"] >= 0
    eng.close()

    # empty stats: every sampling figure is null, never NaN, and the
    # merged rollup re-derives means from summed counters
    empty = ServingStats(slots=1)
    es = empty.summary()
    assert es["n_sampled_requests"] == 0
    assert es["mean_temperature"] is None and es["nll_p50"] is None
    merged = ServingStats.merge([eng.stats, empty])
    assert merged["n_sampled_requests"] == 1
    assert merged["mean_temperature"] == pytest.approx(0.6, abs=1e-4)
    assert merged["nll_p50"] is not None

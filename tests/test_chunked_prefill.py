"""Chunked prefill (ISSUE 14): interleaved long-prompt admission.

``InferenceEngine(prefill_chunk=C)`` splits every admitted prompt into
fixed C-token chunks run through the ONE paged ``extend[b{C}]`` program,
one chunk per engine iteration at the prefill-overlap seam.  The
decisive properties:

* PARITY — chunked output is token-identical to the whole-prompt engine,
  greedy and sampled, at every chunk size, with and without radix
  sharing (the chunk schedule changes WHEN cache rows fill, never what
  they hold).
* LONG PROMPTS — prompts past every bucket admit (up to
  ``max_len - max_new``) with zero new compiled programs; the submit
  error with chunking OFF names ``prefill_chunk=`` as the fix.
* PREFILLING — the transient state is invisible to decode (co-resident
  streams are unchanged), survives ``close()`` mid-chunk, and drains
  its pages.
* RADIX BOUNDARY — a partial radix hit landing exactly on a chunk
  boundary resumes at the divergence page: parity with the cold serve,
  no double-prefilled pages, refcounts drain to zero.
* DETERMINISM — one ``serving-admit`` chaos event per admission attempt
  (stall retries do not re-fire), chunk dispatches add no events.
* STATS — ``n_prefill_chunks`` / ``chunk_stall_s`` /
  ``longest_prompt_admitted`` are exact, merge correctly, and the
  record stays strict-JSON.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    SamplingParams,
    ServingStats,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import CompileTracker

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)

PROMPTS = [
    [1, 2, 3, 4, 5],
    [7, 8],
    [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
    [3, 1, 4, 1, 5, 9, 2, 6],
]


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("buckets", (8, 16))
    kw.setdefault("kv_page_size", 4)
    return InferenceEngine(model, params, **kw)


def _serve(model, params, prompts=PROMPTS, max_new=6, sampling=None, **kw):
    eng = _engine(model, params, **kw)
    if not isinstance(sampling, (list, tuple)):
        sampling = [sampling] * len(prompts)
    reqs = [eng.submit(np.asarray(p, np.int32), max_new=max_new, sampling=s)
            for p, s in zip(prompts, sampling)]
    eng.run(max_steps=2000)
    return eng, reqs


def _outputs(reqs):
    return [(r.status, tuple(r.generated)) for r in reqs]


# ----------------------------------------------------------------------
# construction-time contract


def test_prefill_chunk_validation():
    model, params = _model_and_params()
    with pytest.raises(ValueError, match="prefill_chunk"):
        _engine(model, params, prefill_chunk=-1)
    with pytest.raises(ValueError, match="kv_page_size"):
        InferenceEngine(model, params, slots=2, max_len=48, buckets=(8,),
                        prefill_chunk=4)  # dense layout
    with pytest.raises(ValueError, match="max_len"):
        _engine(model, params, prefill_chunk=64)
    with pytest.raises(ValueError, match="prefix"):
        _engine(model, params, prefill_chunk=4, prefix_cache_bytes=1 << 20)
    # a chunk-lifted scheduler wired to a whole-prompt engine is the
    # drift bug the agreement check exists to catch
    sched = FIFOScheduler(max_len=48, buckets=(8, 16), chunked_prefill=True)
    with pytest.raises(ValueError, match="prefill_chunk"):
        InferenceEngine(model, params, slots=2, max_len=48,
                        kv_page_size=4, scheduler=sched)


# ----------------------------------------------------------------------
# parity: the chunk schedule never changes a token


@pytest.mark.parametrize("chunk", [2, 5, 16])
def test_chunked_matches_whole_prompt_greedy(chunk):
    model, params = _model_and_params(seed=1)
    _, ref = _serve(model, params)
    eng, got = _serve(model, params, prefill_chunk=chunk)
    assert _outputs(got) == _outputs(ref)
    assert all(r.status == "done" for r in got)
    # exact chunk count needs radix OFF (sharing legitimately skips the
    # matched-prefix chunks — the boundary test pins that arithmetic)
    eng2, got2 = _serve(model, params, prefill_chunk=chunk,
                        radix_cache=False)
    assert _outputs(got2) == _outputs(ref)
    s = eng2.stats.summary()
    assert s["n_prefill_chunks"] == sum(
        -(-len(p) // chunk) for p in PROMPTS)


def test_chunked_matches_whole_prompt_sampled():
    """Seeded sampled streams are pure functions of the seed — the chunk
    schedule must not perturb the key schedule or the first pick."""
    model, params = _model_and_params(seed=2)
    mix = [SamplingParams(temperature=0.9, top_p=0.85, top_k=6, seed=i * 3 + 1)
           for i in range(len(PROMPTS) - 1)] + [None]
    _, ref = _serve(model, params, sampling=mix)
    _, got = _serve(model, params, sampling=mix, prefill_chunk=3)
    assert _outputs(got) == _outputs(ref)
    lp_ref = [list(r.logprobs) for r in ref]
    lp_got = [list(r.logprobs) for r in got]
    for a, b in zip(lp_got, lp_ref):
        np.testing.assert_allclose(a, b, atol=1e-5)


# ----------------------------------------------------------------------
# long prompts: past every bucket, one program family


def test_long_prompt_admits_and_census_pinned():
    model, params = _model_and_params(seed=3)
    eng = _engine(model, params, prefill_chunk=4)
    eng.prewarm()
    before = eng._compile.snapshot()
    long_prompt = list(range(1, 41))                 # 40 tokens, bucket 16
    reqs = [eng.submit(np.asarray(long_prompt, np.int32), max_new=5),
            eng.submit([1, 2, 3], max_new=5)]
    eng.run(max_steps=2000)
    assert all(r.status == "done" and len(r.generated) == 5 for r in reqs)
    d = CompileTracker.delta(eng._compile.snapshot(), before)
    assert d["n_compiled_programs"] == 0, d          # extend[b4] prewarmed
    s = eng.stats.summary()
    assert s["longest_prompt_admitted"] == 40
    assert s["n_prefill_chunks"] == 10 + 1           # ceil(40/4) + ceil(3/4)
    assert s["chunk_stall_s"] > 0.0
    eng.close()


def test_scheduler_submit_error_paths():
    """Chunking OFF: an over-bucket prompt's error names prefill_chunk=
    as the fix.  Chunking ON: the same prompt admits, and the cache-length
    bound (max_len - max_new) still holds."""
    off = FIFOScheduler(max_len=48, buckets=(8, 16))
    with pytest.raises(ValueError, match="prefill_chunk"):
        off.submit(list(range(20)), max_new=4)
    on = FIFOScheduler(max_len=48, buckets=(8, 16), chunked_prefill=True)
    req = on.submit(list(range(40)), max_new=8)      # 40 + 8 = max_len
    assert req.bucket == 16                          # capped label
    with pytest.raises(ValueError, match="cache length"):
        on.submit(list(range(41)), max_new=8)        # 41 + 8 > max_len


# ----------------------------------------------------------------------
# PREFILLING state: invisible to decode, safe to close, pages drain


def test_close_mid_chunking_drains_pages():
    model, params = _model_and_params(seed=4)
    eng = _engine(model, params, prefill_chunk=2, radix_cache=False)
    req = eng.submit(np.asarray(list(range(1, 31)), np.int32), max_new=4)
    eng.step()                                       # admit + first chunk
    assert req.status == "prefilling"
    assert eng._slot_prefill[0] is not None
    eng.close()
    assert req.status == "cancelled" and req.engine_fault
    assert eng._pool.allocated == 0                  # every page came back


def test_pool_drains_after_chunked_run():
    model, params = _model_and_params(seed=5)
    eng, reqs = _serve(model, params, prefill_chunk=3, radix_cache=False)
    assert all(r.status == "done" for r in reqs)
    assert eng._pool.allocated == 0
    eng.close()


def test_chunked_overcommit_stalls_then_serves():
    """A pool too small for both slots' worst case: the second admission
    parks on the dry pool and retries — every request still finishes and
    exactly one serving-admit chaos event fired per admission ATTEMPT
    (the stall retry does not re-fire)."""
    model, params = _model_and_params(seed=6)
    plan = FaultPlan(faults=(
        FaultSpec(site="serving-admit", kind="poison", at=(2,)),))
    inj = FaultInjector(plan)                        # event 2 = 3rd attempt
    eng = _engine(model, params, prefill_chunk=4, radix_cache=False,
                  max_len=32, kv_pages=9, chaos=inj)
    reqs = [eng.submit(np.asarray(p, np.int32), max_new=4)
            for p in ([1] * 20, [2] * 20, [3] * 5)]
    eng.run(max_steps=4000)
    statuses = [r.status for r in reqs]
    assert statuses[0] == "done" and statuses[1] == "done"
    # the THIRD admission attempt (not a stall retry of an earlier one)
    # ate the injected fault — stall retries skipping the chaos site is
    # exactly what keeps this index stable
    assert statuses[2] == "failed" and "ChaosFault" in reqs[2].error
    assert eng._pool.allocated == 0
    eng.close()


# ----------------------------------------------------------------------
# radix partial hit landing exactly on a chunk boundary


def test_radix_hit_on_chunk_boundary_parity_and_refcounts():
    """Two waves share a 12-token prefix; page size 4, chunk 4: the
    second wave's match lands exactly on a chunk boundary (done = 12,
    divergence at page 3).  Output must equal the cold serve, no page is
    prefilled twice (radix_hit_tokens says the extend skipped the
    match), and every trie refcount drains to zero after retirement."""
    model, params = _model_and_params(seed=7)
    shared = list(range(1, 13))                      # 3 whole pages
    wave = [shared + [13, 14, 15], shared + [9, 9], [5, 5, 5]]

    # slots=1 serializes the wave so request 1 admits AFTER request 0's
    # donation — its 12-token match is the chunk-boundary landing
    cold_eng, cold = _serve(model, params, prompts=wave, max_new=5,
                            radix_cache=False, prefill_chunk=4, slots=1)
    eng, got = _serve(model, params, prompts=wave, max_new=5,
                      radix_cache=True, prefill_chunk=4, slots=1)
    assert _outputs(got) == _outputs(cold)
    s = eng.stats.summary()
    assert s["radix_hits"] >= 1
    # the matched pages were SKIPPED, not re-extended: chunks dispatched
    # for request 1 cover only its suffix past the 12-token boundary
    assert got[1].radix_tokens == 12
    chunks_cold = cold_eng.stats.summary()["n_prefill_chunks"]
    assert s["n_prefill_chunks"] == chunks_cold - 3  # 12/4 skipped chunks
    # refcounts drain: after the run only the trie's own donations hold
    # pages, and every node's refcount is zero (nothing is pinned)
    assert eng._pool.allocated == eng._radix.n_blocks
    stack = [eng._radix.root]
    while stack:
        node = stack.pop()
        assert node.ref == 0
        stack.extend(node.children.values())
    eng.close()


# ----------------------------------------------------------------------
# stats: exact counters, merge, strict JSON


def test_chunked_stats_merge_and_strict_json():
    model, params = _model_and_params(seed=8)
    eng_a, _ = _serve(model, params, prompts=[[1, 2, 3, 4, 5]],
                      prefill_chunk=2)
    eng_b, _ = _serve(model, params, prompts=[list(range(1, 20))],
                      prefill_chunk=2)
    plain = ServingStats(slots=2)                    # no chunk activity
    a, b = eng_a.stats, eng_b.stats
    for rec, want_chunks, want_longest in ((a, 3, 5), (b, 10, 19)):
        s = rec.summary()
        assert s["n_prefill_chunks"] == want_chunks
        assert s["longest_prompt_admitted"] == want_longest
        assert s["chunk_stall_s"] >= 0.0 and s["chunk_stall_frac"] >= 0.0
    merged = ServingStats.merge([a, b, plain])
    assert merged["n_prefill_chunks"] == 13
    assert merged["longest_prompt_admitted"] == 19   # max, not sum
    assert merged["chunk_stall_s"] == pytest.approx(
        a.summary()["chunk_stall_s"] + b.summary()["chunk_stall_s"], abs=1e-5)
    # the idle record reports the null states, never NaN
    ps = plain.summary()
    assert ps["n_prefill_chunks"] == 0
    assert ps["chunk_stall_frac"] is None
    assert ps["longest_prompt_admitted"] is None
    # strict JSON round-trip: no NaN/Inf anywhere in either record shape
    for payload in (merged, ps):
        assert json.loads(
            json.dumps(payload, allow_nan=False)) == json.loads(
                json.dumps(payload, allow_nan=False))
    eng_a.close()
    eng_b.close()

"""Decode-ahead windows, prompt prefix cache, and prefill overlap (ISSUE 5).

The decisive properties:

* WINDOW PARITY — ``make_decode_window`` (a lax.scan of k fused
  decode+pick steps) emits exactly the tokens k sequential
  ``make_decode_step`` calls emit, and the engine's greedy output is
  token-for-token identical to ``make_generator`` for EVERY
  ``decode_ahead`` — the speedup is bought with fewer host syncs, never
  with different tokens.
* BOUNDED WASTE — EOS/budget/deadline retirement mid-window discards the
  ≤k−1 overrun tokens (never delivered, never counted) and the KV cursor
  clamps at ``max_len`` so overrun writes stay inside the row.
* PREFIX CACHE — a hit reuses the stored prefill row + last-position
  logits (prefill dispatch skipped, output identical — every admission
  re-picks its own first token, so the cache is sampling-safe; ISSUE 13
  lifted the old greedy-only construction guard); the LRU is
  byte-bounded.
* CONTRACT — the chaos ``serving-step`` site counts WINDOWS (one event
  per dispatch, stable across k) and the engine/scheduler bucket sets
  cannot silently drift apart.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
    make_decode_step,
    make_decode_window,
    make_generator,
    make_prefill,
)
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    PrefixCache,
    ServingStats,
)

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


class _FakeClock:
    """Deterministic injectable clock for deadline tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 48)
    kw.setdefault("buckets", (8,))
    return InferenceEngine(model, params, **kw)


# ----------------------------------------------------------------------
# the window primitive (core/generate.py)


def test_decode_window_matches_stepwise():
    """One make_decode_window call == k sequential make_decode_step calls:
    same cache evolution, same tokens, and `last` is the final column."""
    model, params = _model_and_params(seed=1)
    prompts = [np.asarray([7, 3, 11, 2, 5], np.int32),
               np.asarray([4, 9], np.int32)]
    bucket, max_len, k = 8, 32, 5
    batch = np.zeros((2, bucket), np.int32)
    lens = np.asarray([p.size for p in prompts], np.int32)
    for i, p in enumerate(prompts):
        batch[i, : p.size] = p

    prefill = make_prefill(model, max_len)
    cache0, last = prefill(params, jnp.asarray(batch), jnp.asarray(lens))
    tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)

    step = make_decode_step(model, max_len, ragged=True)
    cache, tok = cache0, tok0
    want = []
    for _ in range(k):
        cache, logits = step(params, cache, tok)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        want.append(np.asarray(tok))
    want = np.stack(want, axis=1)  # (2, k)

    win = make_decode_window(model, max_len, window=k)
    # re-prefill: the stepwise loop above consumed cache0's buffers
    cache0, last = prefill(params, jnp.asarray(batch), jnp.asarray(lens))
    _, blk, last_tok = win(params, cache0, tok0)
    np.testing.assert_array_equal(np.asarray(blk), want)
    np.testing.assert_array_equal(np.asarray(last_tok), want[:, -1])


def test_decode_window_active_mask_and_validation():
    """Inactive rows emit pad_id for the whole window (their cache rows
    still advance in lockstep — wasted FLOPs, never corruption), and the
    constructor rejects a nonsensical window."""
    model, params = _model_and_params(seed=2)
    max_len, k, pad = 24, 3, 0
    prefill = make_prefill(model, max_len)
    prompt = jnp.asarray([[5, 6, 7, 8], [1, 2, 3, 4]], jnp.int32)
    cache, last = prefill(params, prompt)
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    win = make_decode_window(model, max_len, window=k, pad_id=pad)
    _, blk, _ = win(params, cache, tok, active=jnp.asarray([True, False]))
    blk = np.asarray(blk)
    assert (blk[1] == pad).all()          # masked row: pad all the way
    assert (blk[0] != pad).any() or True  # live row decoded normally
    with pytest.raises(ValueError, match="window"):
        make_decode_window(model, max_len, window=0)
    with pytest.raises(ValueError, match="temperature"):
        make_decode_window(model, max_len, window=2, top_k=3)


# ----------------------------------------------------------------------
# engine parity across k


def test_engine_parity_across_decode_ahead():
    """Greedy engine output is token-identical to the one-shot generator
    for every decode_ahead — including k that does NOT divide any budget
    and k larger than the shortest budget — while the window count drops
    ~k-fold."""
    model, params = _model_and_params(seed=3)
    prompts = [np.asarray([1, 2, 3, 4, 5], np.int32),
               np.asarray([6, 7], np.int32),
               np.asarray([8, 9, 10], np.int32),
               np.asarray([11, 12, 13, 14], np.int32)]
    budgets = [7, 13, 5, 10]
    gen = make_generator(model, max_len=48, max_new=max(budgets))
    want = [
        np.asarray(gen(params, jnp.asarray(p)[None, :]))[0, p.size: p.size + mn]
        for p, mn in zip(prompts, budgets)
    ]

    windows = {}
    for k in (1, 2, 4, 8):
        eng = _engine(model, params, decode_ahead=k)
        reqs = [eng.submit(p, max_new=mn) for p, mn in zip(prompts, budgets)]
        eng.run()
        for i, (r, w) in enumerate(zip(reqs, want)):
            assert r.status == "done"
            np.testing.assert_array_equal(
                np.asarray(r.generated), w, err_msg=f"k={k} req {i}")
        windows[k] = eng.stats.summary()["n_windows"]
    assert windows[8] < windows[4] < windows[2] < windows[1]


def test_eos_budget_retire_mid_window_and_waste_accounting():
    """A row stopping mid-window (EOS or budget) keeps tokens up to and
    including the stop, discards the ≤k−1 overrun, and the discard shows
    up in window_waste_steps — while parity with the k=1 engine holds."""
    model, params = _model_and_params(seed=4)
    prompt = np.asarray([3, 1, 4, 1, 5], np.int32)

    base = _engine(model, params, decode_ahead=1)
    rb = base.submit(prompt, max_new=9)
    base.run()

    # eos_id chosen as the greedy 4th token -> retirement mid-window
    eos = int(rb.generated[3])
    stop_at = next(i for i, t in enumerate(rb.generated) if t == eos)

    # with eos_id set, ANY k must emit the base stream truncated at the
    # first EOS (inclusive) — no separate k=1-with-eos engine needed
    for k in (4, 8):
        engk = _engine(model, params, decode_ahead=k, eos_id=eos)
        rk = engk.submit(prompt, max_new=9)
        engk.run()
        assert rk.status == "done"
        assert list(rk.generated) == list(rb.generated[: stop_at + 1])
        assert len(rk.generated) == stop_at + 1  # EOS kept, overrun dropped
        s = engk.stats.summary()
        assert s["window_waste_steps"] > 0
        assert s["window_waste_frac"] > 0

    # budget not a multiple of k: exactly max_new tokens, never more
    eng = _engine(model, params, decode_ahead=4)
    r = eng.submit(prompt, max_new=6)  # 1 prefill token + 5 windowed
    eng.run()
    assert len(r.generated) == 6
    assert list(r.generated) == list(rb.generated[:6])
    assert eng.stats.summary()["window_waste_steps"] > 0


def test_cursor_clamps_at_max_len_under_window_overrun():
    """A tight cache (max_len == bucket + max_new) with k not dividing
    max_new forces the frozen-mask overrun to run the cursor INTO the
    clamp (models/transformer.py); output parity and the cursor cap prove
    the overrun stayed inside the row."""
    model, params = _model_and_params(seed=5)
    prompt = np.asarray([2, 7, 1], np.int32)
    bucket, max_new = 8, 6
    max_len = bucket + max_new  # zero slack: any overrun would run off
    gen = make_generator(model, max_len=max_len, max_new=max_new)
    want = np.asarray(gen(params, jnp.asarray(prompt)[None, :]))[0, 3:]
    eng4 = _engine(model, params, decode_ahead=4, max_len=max_len,
                   buckets=(bucket,))
    r4 = eng4.submit(prompt, max_new=max_new)
    eng4.run()
    np.testing.assert_array_equal(np.asarray(r4.generated), want)
    for leaf in jax.tree.leaves(eng4.cache):
        if leaf.ndim == 1 and jnp.issubdtype(leaf.dtype, jnp.integer):
            assert int(leaf.max()) <= max_len  # per-slot cursors clamped


def test_deadline_expiry_mid_flight_cancels():
    """A running request whose deadline lapses between windows is
    cancelled (partial output kept); an overlap-prefilled pending whose
    deadline lapses before a slot frees is cancelled at landing."""
    model, params = _model_and_params(seed=6)
    clock = _FakeClock()

    # running-row cancellation: the callback advances the fake clock past
    # the deadline mid-generation
    eng = _engine(model, params, decode_ahead=4, clock=clock,
                  slots=1, max_len=64)
    eng.scheduler.clock = clock

    def tick(req, tok):
        clock.t += 3.0

    r = eng.submit(np.asarray([1, 2, 3], np.int32), max_new=30,
                   deadline_s=10.0, callback=tick)
    eng.run()
    assert r.status == "cancelled"
    assert 0 < len(r.generated) < 30

    # pending-overdue: slots=1 busy with a long request; the second
    # request is overlap-prefilled behind a window, then its deadline
    # lapses before the slot frees -> cancelled at landing, never run
    clock2 = _FakeClock()
    eng2 = _engine(model, params, decode_ahead=2, clock=clock2,
                   slots=1, max_len=64)
    eng2.scheduler.clock = clock2

    def slow(req, tok):
        clock2.t += 5.0

    long = eng2.submit(np.asarray([4, 5, 6], np.int32), max_new=12,
                       callback=slow)
    short = eng2.submit(np.asarray([7, 8], np.int32), max_new=4,
                        deadline_s=8.0)
    eng2.run()
    assert long.status == "done"
    assert short.status == "cancelled"
    assert short.generated == []  # prefilled but never landed


def test_prefill_overlap_preserves_fifo_and_output():
    """With more requests than slots the engine overlap-prefills behind
    in-flight windows; completion set, per-request output, and admission
    order (FIFO) all match the no-overlap semantics."""
    model, params = _model_and_params(seed=7)
    prompts = [np.asarray([i + 1, i + 2, i + 3], np.int32) for i in range(6)]
    gen = make_generator(model, max_len=48, max_new=6)
    want = [np.asarray(gen(params, jnp.asarray(p)[None, :]))[0, 3:9]
            for p in prompts]
    eng = _engine(model, params, decode_ahead=2, slots=2)
    reqs = [eng.submit(p, max_new=6) for p in prompts]
    eng.run()
    for i, (r, w) in enumerate(zip(reqs, want)):
        assert r.status == "done", f"req {i}"
        np.testing.assert_array_equal(np.asarray(r.generated), w,
                                      err_msg=f"req {i}")
    admits = [r.admit_t for r in reqs]
    assert admits == sorted(admits)  # FIFO admission preserved


# ----------------------------------------------------------------------
# prefix cache


def test_prefix_cache_hit_skips_prefill_with_identical_output():
    """The second identical prompt hits the cache: the prefill dispatch
    count stays flat, the hit is visible in stats, and the output is
    token-identical to the cold run."""
    model, params = _model_and_params(seed=8)
    prompt = np.asarray([9, 4, 2, 6], np.int32)

    eng = _engine(model, params, decode_ahead=2, prefix_cache_bytes=64 << 20)
    calls = {"n": 0}
    real = eng._dense_prefill

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    eng._dense_prefill = counting
    r1 = eng.submit(prompt, max_new=5)
    eng.run()
    assert calls["n"] == 1
    r2 = eng.submit(prompt, max_new=5)
    r3 = eng.submit(prompt, max_new=3)  # same prompt, different budget
    eng.run()
    assert calls["n"] == 1  # both later prefills skipped
    assert list(r2.generated) == list(r1.generated)
    assert list(r3.generated) == list(r1.generated)[:3]
    s = eng.stats.summary()
    assert s["prefix_hits"] == 2 and s["prefix_misses"] == 1
    assert s["prefix_hit_rate"] == pytest.approx(2 / 3, abs=1e-4)

    # different bucket => different content address, no false hit
    sched = FIFOScheduler(max_len=64, buckets=(8, 16))
    a = sched.submit(np.arange(1, 7, dtype=np.int32), max_new=2)   # bucket 8
    b = sched.submit(np.arange(1, 12, dtype=np.int32), max_new=2)  # bucket 16
    assert a.prefix_key != b.prefix_key


def test_prefix_cache_lru_eviction_and_refusals():
    """Unit contract of the byte-bounded LRU: eviction order, oversized
    refusal — and the ISSUE 13 lift of the old greedy-only engine guard."""
    row = {"k": np.zeros((64,), np.float32)}  # 256 bytes per entry
    pc = PrefixCache(max_bytes=600)
    pc.put("a", row, 1)
    pc.put("b", row, 2)
    assert pc.get("a") is not None  # refresh a -> b is now LRU
    pc.put("c", row, 3)             # 3*256 > 600: evicts b
    assert pc.get("b") is None
    assert pc.get("a") is not None and pc.get("c") is not None
    assert pc.bytes <= 600

    big = {"k": np.zeros((1024,), np.float32)}  # 4096 bytes > budget
    pc.put("huge", big, 4)
    assert pc.get("huge") is None  # refused, cache untouched
    assert pc.get("a") is not None

    with pytest.raises(ValueError, match="max_bytes"):
        PrefixCache(max_bytes=0)

    model, params = _model_and_params(seed=9)
    # ISSUE 13 lifted the old cache+sampling refusal: the cache stores
    # only deterministic prefill products (row + logits) and every
    # admission re-picks its own first token, so this must now construct
    eng = _engine(model, params, prefix_cache_bytes=1 << 20,
                  temperature=0.7, rng=jax.random.PRNGKey(0))
    eng.close()


# ----------------------------------------------------------------------
# contracts: buckets, chaos, stats


def test_engine_scheduler_bucket_contract():
    """buckets= without a scheduler builds one; buckets= WITH a scheduler
    must agree (drift is rejected, not resolved); scheduler.max_len must
    match the engine's."""
    model, params = _model_and_params(seed=10)
    eng = _engine(model, params, buckets=(8, 16), max_len=64)
    assert eng.buckets == (8, 16)
    assert eng.scheduler.buckets == (8, 16)

    sched = FIFOScheduler(max_len=64, buckets=(8, 16))
    ok = InferenceEngine(model, params, slots=2, max_len=64,
                         scheduler=sched, buckets=(16, 8))  # order-insensitive
    assert ok.buckets == (8, 16)
    with pytest.raises(ValueError, match="buckets"):
        InferenceEngine(model, params, slots=2, max_len=64,
                        scheduler=FIFOScheduler(max_len=64, buckets=(8, 16)),
                        buckets=(8, 32))
    with pytest.raises(ValueError, match="max_len"):
        InferenceEngine(model, params, slots=2, max_len=48,
                        scheduler=FIFOScheduler(max_len=64, buckets=(8,)))


def test_chaos_serving_step_counts_windows_not_steps():
    """The serving-step chaos site consumes ONE event per window dispatch:
    a transient fault inside a decode_ahead window is absorbed by the
    watchdog with exact output parity, and the event count equals the
    window count (stable across k, so seeded plans replay)."""
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )

    model, params = _model_and_params(seed=11)
    prompt = np.asarray([5, 3, 1], np.int32)

    free = _engine(model, params, decode_ahead=4)
    fr = free.submit(prompt, max_new=11)
    free.run()
    clean_windows = free.stats.summary()["n_windows"]

    inj = FaultInjector(FaultPlan(seed=0, faults=(
        FaultSpec(site="serving-step", at=(1,)),)))
    eng = _engine(model, params, decode_ahead=4, chaos=inj,
                  stall_timeout_s=60.0)
    r = eng.submit(prompt, max_new=11)
    eng.run()
    assert r.status == "done"
    assert list(r.generated) == list(fr.generated)
    # one event per dispatch ATTEMPT: the clean windows + the faulted one
    assert inj.events("serving-step") == clean_windows + 1
    assert inj.summary()["faults_injected"] == 1


def test_stats_window_fields_strict_json_round_trip():
    """The new window/waste/prefix fields survive a STRICT json round trip
    (allow_nan=False — no NaN/Inf smuggled into the metrics record) and
    the ratio fields are None, not NaN, when their denominators are 0."""
    st = ServingStats(slots=3, decode_ahead=4)
    empty = st.summary()
    assert empty["window_waste_frac"] is None
    assert empty["prefix_hit_rate"] is None
    json.loads(json.dumps(empty, allow_nan=False))

    st.window(0.002, 0.001, steps=12, waste=3)
    st.window(0.001, 0.0005, steps=8, waste=0)
    st.prefix(True)
    st.prefix(False)
    st.prefix(True)
    s = st.summary()
    assert s["decode_ahead"] == 4
    assert s["n_windows"] == 2
    assert s["window_steps"] == 20
    assert s["window_waste_steps"] == 3
    assert s["window_waste_frac"] == pytest.approx(0.15)
    assert s["prefix_hits"] == 2 and s["prefix_misses"] == 1
    assert s["prefix_hit_rate"] == pytest.approx(2 / 3, abs=1e-4)
    round_tripped = json.loads(json.dumps(s, allow_nan=False))
    assert round_tripped["n_windows"] == 2


def test_engine_rejects_bad_decode_ahead():
    model, params = _model_and_params(seed=12)
    with pytest.raises(ValueError, match="decode_ahead"):
        _engine(model, params, decode_ahead=0)


# ----------------------------------------------------------------------
# bench harness smoke (slow: subprocess + fresh jax init)


@pytest.mark.slow
def test_bench_serving_quick_smoke():
    """DTM_BENCH_QUICK=1 runs the full bench harness (all four legs) in
    CI-smoke sizes: the JSON record must carry the decode-ahead and
    prefix-cache legs with ZERO output mismatches — harness rot in the
    measurement code fails here instead of silently in a nightly."""
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_serving.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", DTM_BENCH_QUICK="1")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["quick"] is True
    da = rec["decode_ahead"]
    assert da["output_mismatches"] == 0
    assert da["speedup_best_k"] is not None  # parity held -> reported
    assert set(da["legs"]) >= {"1", "2", "4"}
    for leg in da["legs"].values():
        assert leg["n_windows"] > 0
    pc = rec["prefix_cache"]
    assert pc["output_mismatches"] == 0
    assert pc["prefills_skipped"] > 0
    assert rec["engine_over_static"] is not None
    # ISSUE 6 legs: the compile census must show repeats compiling zero
    # new programs and the new bucket compiling some (when the compile
    # hook is available at all), and the tracer-overhead leg must report
    # a finite comparison (the <=2% budget itself is a bench figure — a
    # loaded CI host can't pin a 2% wall-clock delta reliably)
    census = rec["compile_census"]
    if census["mode"] != "unavailable":
        assert census["repeat_compiles_zero"] is True
        assert census["new_bucket_compiles"] is True
        assert census["legs"]["bucket16_first"]["n_new_programs"] > 0
        # ISSUE 7: pinned-budget regression gate (a breach exits the
        # bench nonzero, so returncode==0 above already implies this)
        assert census["census_ok"] is True, census["over_budget"]
    # ISSUE 7 satellite: the persistent-compile-cache leg ran its two
    # subprocess probes; cache_effective stays a reported measurement,
    # not an assertion (CPU cacheability varies across jax versions)
    cc = rec["compile_cache"]
    assert ("error" in cc) or (cc["cold_wall_s"] > 0 and cc["warm_wall_s"] > 0)
    ov = rec["tracer_overhead"]
    assert ov["off_s"] > 0 and ov["on_s"] > 0
    assert ov["n_trace_events"] > 0 and ov["dropped_events"] == 0

"""Paged KV cache + radix prefix sharing (serving/kv_pool.py,
serving/radix_cache.py, the engine's ``kv_page_size=`` path — ISSUE 7).

The decisive properties:

* PARITY — greedy decode through the PAGED engine (pool + block tables +
  gather/scatter attention) is token-for-token identical to the dense
  engine for every ``decode_ahead``, under mixed retirement (EOS / budget
  / deadline), and with ``kv_cache_dtype="int8"`` quantized pages.
* SHARING — the radix trie serves repeated prompt prefixes from shared
  refcounted pages (prefill compute skipped for the match), with output
  still dense-identical; divergence never corrupts a shared page (COW by
  block-table remapping).
* OVERCOMMIT — a pool smaller than ``slots * max_len`` stalls admission
  when dry (never fails, never corrupts) and every request still
  completes, with identical tokens.
* ACCOUNTING — pages drain back to the pool at retirement; ServingStats'
  page/radix fields are strict-JSON-safe; chaos per-site event counts are
  unchanged by the cache layout (paging is invisible to fault schedules).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    KVPagePool,
    PrefixCache,
    RadixCache,
    pages_needed,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)

KW = dict(num_classes=16, dim=64, depth=2, heads=4, dtype=jnp.float32)

PROMPTS = [
    [1, 2, 3, 4, 5],
    [7, 8],
    [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
    [3, 1, 4, 1, 5, 9, 2, 6],
    [2, 7, 1, 8],
    [6, 6, 6],
]


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _run(engine, prompts=PROMPTS, max_new=10, **submit_kw):
    reqs = [engine.submit(p, max_new=max_new, **submit_kw) for p in prompts]
    engine.run()
    return reqs


def _outputs(reqs):
    return [(r.status, tuple(r.generated)) for r in reqs]


# ----------------------------------------------------------------------
# host-side units: page pool + radix trie


def test_page_pool_alloc_free():
    pool = KVPagePool(n_pages=6, page_size=8)
    assert pool.capacity == 5 and pool.free_count == 5 and pool.allocated == 0
    a = pool.alloc(3)
    assert a == [1, 2, 3]  # ascending, page 0 (trash) never handed out
    assert pool.alloc(3) is None  # all-or-nothing: nothing was taken
    assert pool.free_count == 2
    pool.free([2])
    b = pool.alloc(3)
    assert sorted(b) == [2, 4, 5] and pool.free_count == 0
    with pytest.raises(ValueError, match="invalid page id"):
        pool.free([0])  # the trash page is not freeable
    with pytest.raises(ValueError, match="invalid page id"):
        pool.free([6])
    pool.free([1, 3] + b)  # pages 1, 3 from `a` (2 was already returned)
    with pytest.raises(ValueError, match="double free"):
        pool.free([1])


def test_pages_needed():
    assert pages_needed(1, 8) == 1
    assert pages_needed(8, 8) == 1
    assert pages_needed(9, 8) == 2
    assert pages_needed(33, 8) == 5


def test_radix_trie_match_insert_evict():
    rc = RadixCache(page_size=4)
    toks = np.arange(12, dtype=np.int32)
    path, m = rc.match(toks)
    assert path == [] and m == 0
    held, kept = rc.insert(toks, 0, {0: 5, 1: 6, 2: 7}, [])
    assert [n.page for n in held] == [5, 6, 7] and kept == []
    assert rc.n_blocks == 3
    # full and partial matches
    path, m = rc.match(toks)
    assert m == 12 and [n.page for n in path] == [5, 6, 7]
    path, m = rc.match(np.asarray([0, 1, 2, 3, 9, 9, 9, 9], np.int32))
    assert m == 4 and [n.page for n in path] == [5]
    # duplicate insert: existing node wins, the donor keeps its page
    held2, kept2 = rc.insert(toks[:8], 1, {1: 9}, rc.match(toks[:4])[0])
    assert held2 == [] and kept2 == [9]
    # eviction only touches ref==0 LEAF nodes, deepest-LRU first
    rc.release(held)  # drop the donor's refs
    freed = []
    assert rc.evict(1, freed.append) == 1 and freed == [7]
    assert rc.n_blocks == 2
    rc.acquire(rc.match(toks[:4])[0])
    # page 6's node is a leaf with ref 0; page 5's is held -> only 6 frees
    assert rc.evict(5, freed.append) == 1 and freed == [7, 6]
    with pytest.raises(ValueError, match="unheld"):
        rc.release([RadixCache(4).root])


# ----------------------------------------------------------------------
# engine parity: paged == dense, greedily, token for token


@pytest.mark.parametrize("k", [1, 4, 8])
def test_paged_greedy_matches_dense(k):
    model, params = _model_and_params()
    dense = InferenceEngine(model, params, slots=3, max_len=32,
                            decode_ahead=k)
    want = _outputs(_run(dense))
    paged = InferenceEngine(model, params, slots=3, max_len=32,
                            decode_ahead=k, kv_page_size=8,
                            radix_cache=False)
    got = _outputs(_run(paged))
    assert got == want
    s = paged.stats.summary()
    assert s["kv_page_size"] == 8 and s["kv_pages_peak"] > 0


def test_paged_mixed_retirement_matches_dense():
    """EOS, budget, and deadline retirement interleaved mid-window — the
    layouts must agree on every status and every kept token."""
    model, params = _model_and_params(seed=2)
    prompts = [[1, 2, 3], [4, 5, 6, 7], [8, 9], [10, 11, 12, 13, 14]]

    def drive(**kw):
        clock = _FakeClock()
        eng = InferenceEngine(model, params, slots=2, max_len=32, eos_id=2,
                              decode_ahead=4, clock=clock, **kw)
        reqs = [eng.submit(prompts[0], max_new=12),
                eng.submit(prompts[1], max_new=3),
                eng.submit(prompts[2], max_new=12, deadline_s=2.0),
                eng.submit(prompts[3], max_new=6)]
        while eng.has_work:
            eng.step()
            clock.t += 1.0  # the deadline request dies mid-flight
        eng.run()
        return _outputs(reqs)

    want = drive()
    got = drive(kv_page_size=8, radix_cache=False)
    assert got == want
    assert any(st == "cancelled" for st, _ in got)  # the deadline fired
    assert any(st == "done" for st, _ in got)


@pytest.mark.parametrize("radix", [False, True])
def test_paged_int8_matches_dense_int8(radix):
    """int8-quantized pages (payload + per-position scales) reproduce the
    dense int8 engine exactly, with and without radix sharing."""
    model, params = _model_and_params(kv_cache_dtype="int8")
    dense = InferenceEngine(model, params, slots=3, max_len=32)
    want = _outputs(_run(dense))
    paged = InferenceEngine(model, params, slots=3, max_len=32,
                            kv_page_size=8, radix_cache=radix)
    got = _outputs(_run(paged))
    assert got == want


def test_int8_scales_reset_on_slot_reuse():
    """Satellite: ragged serving with int8 must reset the SCALE leaves like
    the payload when a slot retires and is reused — more requests than
    slots forces reuse, and outputs must match a no-reuse engine, on both
    layouts."""
    model, params = _model_and_params(kv_cache_dtype="int8")
    fresh = InferenceEngine(model, params, slots=len(PROMPTS), max_len=32)
    want = _outputs(_run(fresh))
    for kw in ({}, {"kv_page_size": 8, "radix_cache": False}):
        reused = InferenceEngine(model, params, slots=2, max_len=32, **kw)
        got = _outputs(_run(reused))
        assert got == want, f"slot-reuse divergence under {kw or 'dense'}"


# ----------------------------------------------------------------------
# radix sharing: shared prefixes, partial hits, COW at divergence


def test_radix_sharing_matches_dense():
    """A shared-system-prompt workload: the radix engine must emit
    dense-identical tokens while serving the shared pages once."""
    model, params = _model_and_params(seed=3)
    shared = [11, 12, 13, 14, 15, 1, 2, 3]          # exactly one page
    prompts = [shared + [i] for i in range(5)]       # diverge after it
    prompts.append(shared[:4] + [9, 9])              # partial-prefix miss
    dense = InferenceEngine(model, params, slots=2, max_len=32)
    want = _outputs(_run(dense, prompts, max_new=6))
    eng = InferenceEngine(model, params, slots=2, max_len=32, kv_page_size=8)
    reqs = _run(eng, prompts, max_new=6)
    assert _outputs(reqs) == want
    s = eng.stats.summary()
    assert s["radix_hits"] >= 3  # later admissions matched the shared page
    assert s["radix_hit_tokens"] == s["radix_hits"] * 8
    assert [r.radix_tokens for r in reqs][0] == 0  # the first paid prefill


def test_radix_pool_drains_after_run():
    """Retirement returns every private page; only trie-resident blocks
    (ref 0, evictable) may remain allocated."""
    model, params = _model_and_params()
    eng = InferenceEngine(model, params, slots=2, max_len=32, kv_page_size=8)
    _run(eng)
    assert eng._pool.allocated == eng._radix.n_blocks
    # with sharing off the pool drains to exactly zero
    eng2 = InferenceEngine(model, params, slots=2, max_len=32,
                           kv_page_size=8, radix_cache=False)
    _run(eng2)
    assert eng2._pool.allocated == 0


def test_overcommit_stalls_then_completes():
    """A pool that cannot hold every slot's worst case (overcommit) must
    serve the full workload anyway — admission stalls while dry, resumes
    as decode frees pages, and tokens stay dense-identical."""
    model, params = _model_and_params()
    dense = InferenceEngine(model, params, slots=4, max_len=32)
    want = _outputs(_run(dense))
    # 4 slots x 4 pages/slot worst case = 16; give it 8 (+ trash)
    eng = InferenceEngine(model, params, slots=4, max_len=32,
                          kv_page_size=8, kv_pages=9, radix_cache=False)
    reqs = _run(eng)
    assert _outputs(reqs) == want
    assert all(r.status == "done" for r in reqs)
    assert eng.stats.summary()["kv_pages_peak"] <= 8


# ----------------------------------------------------------------------
# construction contracts


def test_paged_constructor_validation():
    model, params = _model_and_params()
    with pytest.raises(ValueError, match="multiple of kv_page_size"):
        InferenceEngine(model, params, slots=2, max_len=30, kv_page_size=8)
    with pytest.raises(ValueError, match="needs the paged cache"):
        InferenceEngine(model, params, slots=2, max_len=32, radix_cache=True)
    with pytest.raises(ValueError, match="needs kv_page_size"):
        InferenceEngine(model, params, slots=2, max_len=32, kv_pages=4)
    with pytest.raises(ValueError, match="cannot hold one full-length"):
        InferenceEngine(model, params, slots=2, max_len=32,
                        kv_page_size=8, kv_pages=3)


# ----------------------------------------------------------------------
# accounting: stats schema, oversized counter, chaos invariance


def test_paged_stats_json_safe():
    model, params = _model_and_params()
    eng = InferenceEngine(model, params, slots=2, max_len=32, kv_page_size=8)
    _run(eng)
    s = eng.stats.summary()
    for key in ("kv_page_size", "kv_pages_total", "kv_pages_live",
                "kv_pages_peak", "kv_bytes_live", "kv_bytes_peak",
                "radix_hits", "radix_misses", "radix_hit_tokens",
                "radix_hit_rate"):
        assert key in s, key
    json.dumps(s, allow_nan=False)  # strict-JSON-safe (no NaN/Inf leaks)
    assert s["kv_pages_total"] == 8  # slots * max_len/ps (trash excluded)
    assert s["kv_bytes_peak"] == s["kv_pages_peak"] * eng._page_bytes
    # the dense engine reports the same schema, nulled/zeroed
    dense = InferenceEngine(model, params, slots=2, max_len=32)
    _run(dense)
    sd = dense.stats.summary()
    assert sd["kv_page_size"] is None and sd["kv_pages_peak"] == 0
    json.dumps(sd, allow_nan=False)


def test_prefix_cache_oversized_counter():
    """Satellite: an entry bigger than the whole budget is refused AND
    counted — sizing bugs surface in stats instead of silently thrashing
    the LRU."""
    cache = PrefixCache(max_bytes=64)
    row = {"k": np.zeros((1, 128), np.float32)}  # 512B > 64B budget
    cache.put("a", row, 3)
    assert len(cache) == 0 and cache.bytes == 0 and cache.oversized == 1
    cache.put("b", row, 4)
    assert cache.oversized == 2
    # the engine folds the counter into its stats record
    model, params = _model_and_params()
    eng = InferenceEngine(model, params, slots=2, max_len=32,
                          prefix_cache_bytes=8)  # every row is oversized
    _run(eng, PROMPTS[:3])
    assert eng.stats.summary()["prefix_oversized"] == 3


def test_chaos_event_counts_paging_invariant():
    """The fault-injection contract: per-site event indices depend on the
    request stream, not the cache layout — a seeded plan replays
    identically against dense and paged engines."""
    model, params = _model_and_params()
    counts = {}
    for name, kw in (("dense", {}),
                     ("paged", {"kv_page_size": 8})):
        inj = FaultInjector(FaultPlan())  # count events, fire nothing
        eng = InferenceEngine(model, params, slots=2, max_len=32,
                              chaos=inj, **kw)
        _run(eng)
        counts[name] = {s: inj.events(s)
                        for s in ("serving-admit", "serving-step",
                                  "serving-callback")}
    assert counts["paged"] == counts["dense"]
    assert counts["dense"]["serving-admit"] == len(PROMPTS)


def test_chaos_admit_poison_isolated_on_paged():
    """An injected admission poison on the paged engine fails only its
    request and leaks no pages."""
    model, params = _model_and_params()
    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-admit", kind="poison", at=(1,)),)))
    eng = InferenceEngine(model, params, slots=2, max_len=32,
                          kv_page_size=8, radix_cache=False, chaos=inj)
    reqs = _run(eng, PROMPTS[:4])
    assert [r.status for r in reqs] == ["done", "failed", "done", "done"]
    assert eng._pool.allocated == 0  # every page came back


# ----------------------------------------------------------------------
# bench harness smoke (slow: subprocess + fresh jax init)


@pytest.mark.slow
def test_bench_kv_paging_quick_smoke():
    """The equal-HBM concurrency bench end to end in CI-smoke sizes: the
    paged+radix leg must serve >= 2x the dense leg's peak concurrent
    sessions at ~equal KV bytes with token-identical greedy output — the
    script itself exits nonzero when either gate fails."""
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_kv_paging.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", DTM_BENCH_QUICK="1")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"] is True
    assert rec["outputs_match"] is True
    assert rec["concurrency_ratio"] >= 2.0
    assert 0.9 <= rec["bytes_ratio"] <= 1.1  # the budget really was fixed
    assert rec["paged"]["radix_hit_tokens"] > 0


def test_close_fails_overcommit_stalled_request_and_frees_pages():
    """Satellite fix (ISSUE 8): close() with a request PARKED on a dry
    page pool (overcommit stall — accepted, prefilled once, starved of
    pages) must fail it TERMINALLY: status ``failed`` with an error
    naming the stall, ``engine_fault`` set (the engine gave up on work it
    had accepted — a router re-dispatches exactly these), every page
    freed, and nothing left parked.  A queued-never-admitted request
    still reads plain ``cancelled``."""
    model, params = _model_and_params()
    # 2 slots but a pool holding ONE full-length request: the second
    # admission prefills, finds the pool dry, and parks
    eng = InferenceEngine(model, params, slots=2, max_len=16, kv_page_size=4,
                          kv_pages=5, radix_cache=False,
                          scheduler=FIFOScheduler(max_len=16, buckets=(8,)))
    r1 = eng.submit([1, 2, 3], max_new=12)
    r2 = eng.submit([4, 5, 6], max_new=12)
    eng.step()
    assert r1.status == "running" and r2.status == "queued"
    assert len(eng._pending) == 1  # r2 parked on the dry pool

    eng.close()
    assert r1.status == "cancelled" and r1.engine_fault
    assert r2.status == "failed" and r2.engine_fault
    assert "overcommit-stalled" in (r2.error or "")
    assert eng._pool.allocated == 0 and not eng._pending
    assert len(eng.scheduler) == 0
    # both surfaced exactly once through the terminal stream
    assert {r.id for r in eng.completed} == {r1.id, r2.id}

"""Build-time validation of parallel configs (VERDICT.md r2 item 3).

The sp islands fall back to local full-sequence attention for shapes that
don't divide the mesh — correct for init samples and eval remainders, but a
config whose every TRAINING batch would fall back must be refused at
Trainer build time, not silently degraded on the hot path.  Likewise the
causal flag is derived from the model family (causal_lm is causal unless
explicitly told otherwise), closing the RunConfig(model="causal_lm", sp=4)
bidirectional-LM footgun.
"""

import jax.numpy as jnp
import pytest

from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig


LM = dict(
    model="causal_lm",
    dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 64},
    n_train=256, n_test=64, batch_size=64, epochs=1, quiet=True,
    eval_batch_size=32,
)


def _lm_cfg(heads=4, **kw):
    mk = {"dim": 64, "depth": 1, "heads": heads, "dtype": jnp.float32}
    mk.update(kw.pop("model_kwargs", {}))
    return RunConfig(name="v", model_kwargs=mk, **{**LM, **kw})


def test_ulysses_indivisible_heads_refused(eight_devices):
    with pytest.raises(ValueError, match="heads % sp"):
        Trainer(_lm_cfg(heads=2, dp=2, sp=4, sp_impl="ulysses"))


def test_ulysses_divisible_heads_builds(eight_devices):
    Trainer(_lm_cfg(heads=4, dp=2, sp=4, sp_impl="ulysses"))


def test_seq_len_indivisible_refused(eight_devices):
    # seq_len 60 % sp 8 != 0 -> every hot batch would fall back
    with pytest.raises(ValueError, match="sequence length"):
        Trainer(_lm_cfg(dp=1, sp=8, sp_impl="ring",
                        dataset_kwargs={"vocab": 16, "seq_len": 60}))


def test_microbatch_indivisible_refused(eight_devices):
    # batch 66 / grad_accum 11 = microbatch 6, not divisible by dp=4
    with pytest.raises(ValueError, match="microbatch"):
        Trainer(_lm_cfg(dp=4, sp=2, batch_size=66, grad_accum=11))
    # and the distinct failure gets its own message: batch % grad_accum
    with pytest.raises(ValueError, match="not divisible by\n?.*grad_accum"):
        Trainer(_lm_cfg(dp=1, sp=2, batch_size=65, grad_accum=2))


def test_vit_patch_grid_seq_len_checked(eight_devices):
    # 28x28 images, patch 7 -> S=16; sp=8 divides 16 -> builds
    cfg = RunConfig(
        name="v", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 1, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", n_train=256, n_test=64, batch_size=64, epochs=1,
        quiet=True, eval_batch_size=32, dp=1, sp=8,
    )
    Trainer(cfg)
    # patch 4 -> S=49; 49 % 8 != 0 -> refused
    bad = cfg.replace(model_kwargs={**cfg.model_kwargs, "patch_size": 4})
    with pytest.raises(ValueError, match="sequence length"):
        Trainer(bad)


def test_causal_derived_from_model_family(eight_devices):
    """causal_lm + sp WITHOUT causal=True in the config is still causal."""
    t = Trainer(_lm_cfg(dp=2, sp=4, sp_impl="ring"))
    assert t.causal is True


def test_causal_explicit_model_override_wins(eight_devices):
    """model_kwargs={'causal': False} is the explicit bidirectional opt-out."""
    t = Trainer(_lm_cfg(dp=2, sp=4, sp_impl="ring",
                        model_kwargs={"causal": False}))
    assert t.causal is False


def test_causal_config_flag_still_forces_vit(eight_devices):
    """config.causal=True masks a family that is bidirectional by default."""
    cfg = RunConfig(
        name="v", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 1, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", n_train=256, n_test=64, batch_size=64, epochs=1,
        quiet=True, eval_batch_size=32, dp=1, sp=8, causal=True,
    )
    assert Trainer(cfg).causal is True
    assert Trainer(cfg.replace(causal=False)).causal is False


def test_sp_causal_lm_trains_causal_end_to_end(eight_devices):
    """The derived flag reaches the island: an sp run with NO causal flag
    anywhere matches the explicit causal=True run parameter-for-parameter."""
    import jax
    import numpy as np

    t_implicit = Trainer(_lm_cfg(dp=2, sp=2, sp_impl="ring", epochs=2))
    t_implicit.fit()
    t_explicit = Trainer(_lm_cfg(dp=2, sp=2, sp_impl="ring", epochs=2,
                                 causal=True))
    t_explicit.fit()
    a, b = jax.device_get((t_implicit.state.params, t_explicit.state.params))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=0.0)


def test_causal_tristate_explicit_false_wins(eight_devices):
    """config.causal is tri-state (r3 advisor): None defers to the family
    default, but an EXPLICIT causal=False beats causal_lm's causal=True —
    and lands in the model kwargs so the model's own attn_fn honors it."""
    t = Trainer(_lm_cfg(dp=2, sp=4, sp_impl="ring", causal=False))
    assert t.causal is False
    # non-sp path: the flag must reach the model family's own causal knob
    t2 = Trainer(_lm_cfg(dp=1, causal=False))
    assert t2.causal is False
    assert t2.model.causal is False
    # and unset still derives the family default
    t3 = Trainer(_lm_cfg(dp=1))
    assert t3.causal is True
    assert t3.model.causal is True

"""Context-parallel serving (ISSUE 20): the sequence axis sharded away.

The decisive properties:

* MESH — ``serving_mesh(tp, cp=)`` carves a 2-D cp×tp mesh (cp=1 stays
  the 1-axis tp mesh, bit-compatible with every existing engine), and
  ``tp_device_groups(n, tp, cp=)`` hands out DISJOINT cp·tp-chip groups,
  refusing non-divisible carves with a sized error.
* PARITY — ring-attention prefill + sequence-sharded paged KV at
  cp ∈ {2, 4} (and cp=2 × tp=2) is token-identical to cp=1, across
  int8 KV and speculative decoding — GSPMD moves the bytes, never the
  argmax.
* MEMORY — per-chip KV bytes land at ~1/cp of the cp=1 figure at a
  FIXED pool size; ``ServingStats.memory(cp=)`` rides ``merge`` into
  the rollup (homogeneous cp survives, heterogeneous → None, strict
  JSON).
* LAUNCH/OPS — ``prewarm()`` under a cp mesh compiles the whole
  cp-qualified family (``prefill[b16,cp2]``) so serving compiles ZERO
  programs; chaos event counts are cp-invariant; ``ring_hop`` child
  spans carry the analytic grouped-width comm bytes.
* REFUSALS — dense layout, indivisible max_len/kv_pages, and
  attn_fn-bearing models refuse cp>1 with actionable errors.

The whole file runs on the 8-virtual-CPU-device platform tests/
conftest.py arms (``eight_devices`` skips otherwise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    serving_mesh,
    tp_device_groups,
)
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    ServingStats,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
)

KW = dict(num_classes=16, dim=64, depth=2, heads=4, dtype=jnp.float32)

MAX_LEN = 32
PROMPTS = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 5, 4, 5, 4, 5], [6, 7, 8, 9],
           [2, 4, 2, 4, 2, 4]]


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, cp=1, **ekw):
    ekw.setdefault("kv_page_size", 8)
    return InferenceEngine(
        model, params, slots=2, max_len=MAX_LEN, cp=cp,
        scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,),
                                max_queue=len(PROMPTS)),
        **ekw)


def _serve(model, params, cp=1, max_new=6, prompts=PROMPTS, **ekw):
    eng = _engine(model, params, cp=cp, **ekw)
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.run()
    outs = [list(r.generated) for r in reqs]
    eng.close()
    return outs


@pytest.fixture(scope="module")
def native(eight_devices):
    return _model_and_params()


@pytest.fixture(scope="module")
def int8(eight_devices):
    return _model_and_params(kv_cache_dtype="int8")


@pytest.fixture(scope="module")
def refs(native, int8):
    return {
        "native": _serve(*native, cp=1),
        "int8": _serve(*int8, cp=1),
    }


# ----------------------------------------------------------------------
# mesh carving: the 2-D cp×tp grid and its group factory


@pytest.mark.parametrize("cp,tp", [(1, 2), (2, 1), (2, 2)])
def test_serving_mesh_cp_by_tp_shape(eight_devices, cp, tp):
    mesh = serving_mesh(tp, cp=cp)
    if cp == 1:
        # cp=1 is bit-compatible with the pre-ISSUE-20 1-axis mesh
        assert mesh.axis_names == ("tp",)
        assert mesh.devices.shape == (tp,)
    else:
        assert mesh.axis_names == ("cp", "tp")
        assert mesh.devices.shape == (cp, tp)
    # every device distinct, row-major carve from the default order
    flat = list(mesh.devices.flat)
    assert len(set(flat)) == cp * tp
    assert flat == list(jax.devices()[: cp * tp])


def test_tp_device_groups_cp_disjoint(eight_devices):
    groups = tp_device_groups(2, 2, cp=2)  # 2 groups × (cp2 × tp2) = 8
    assert len(groups) == 2
    assert all(len(g) == 4 for g in groups)
    assert not set(groups[0]) & set(groups[1])
    # each group carves its own cp×tp mesh
    mesh = serving_mesh(2, groups[1], cp=2)
    assert mesh.devices.shape == (2, 2)
    assert set(mesh.devices.flat) == set(groups[1])


def test_tp_device_groups_cp_rejects_non_divisible(eight_devices):
    with pytest.raises(ValueError, match=r"groups x cp x tp"):
        tp_device_groups(3, 2, cp=2)  # 12 > 8 devices
    with pytest.raises(ValueError, match=r"groups x cp x tp"):
        tp_device_groups(2, 2, cp=4)  # 16 > 8
    with pytest.raises(ValueError, match="cp"):
        tp_device_groups(2, 2, cp=0)
    with pytest.raises(ValueError):
        serving_mesh(2, cp=8)  # 16 > 8 devices, error names cp


# ----------------------------------------------------------------------
# parity: curated composition slice, every case vs its cp=1 reference


CASES = [
    # (cp, tp, kv_dtype, speculative)
    (2, 1, "native", False),
    (2, 1, "int8", False),
    (2, 1, "native", True),
    (4, 1, "native", False),
    (2, 2, "native", False),
]


@pytest.mark.parametrize(
    "cp,tp,kvd,spec", CASES,
    ids=[f"cp{c}-tp{t}-{d}-{'spec' if s else 'plain'}"
         for c, t, d, s in CASES])
def test_cp_parity(native, int8, refs, cp, tp, kvd, spec):
    model, params = native if kvd == "native" else int8
    ekw = {"tp": tp} if tp > 1 else {}
    if spec:
        ekw.update(speculative="ngram", draft_len=3)
    assert _serve(model, params, cp=cp, **ekw) == refs[kvd]


# ----------------------------------------------------------------------
# memory: per-chip KV bytes 1/cp at a fixed pool size, stats plumbing


def test_per_chip_kv_bytes_drop_by_cp(native):
    model, params = native
    sizes = {}
    for cp in (1, 2, 4):
        # FIXED pool size divisible by every cp: the ratio measures the
        # sequence sharding, not default kv_pages rounding
        eng = _engine(model, params, cp=cp, kv_pages=16)
        sizes[cp] = eng.kv_bytes_per_chip()
        s = eng.stats.summary()
        assert s["cp"] == cp
        assert s["kv_bytes_per_chip"] == sizes[cp]
        eng.close()
    for cp in (2, 4):
        ratio = sizes[1] / sizes[cp]
        # the replicated block table/index is the honest tax inside ±10%
        assert 0.9 * cp <= ratio <= 1.1 * cp, (cp, ratio)


def test_stats_cp_merges_into_rollup():
    import json

    a, b = ServingStats(2), ServingStats(2)
    a.memory(tp=1, kv_bytes_per_chip=100, weight_bytes_per_chip=1000, cp=2)
    b.memory(tp=1, kv_bytes_per_chip=80, weight_bytes_per_chip=1000, cp=2)
    m = ServingStats.merge([a, b])
    assert m["cp"] == 2
    # cluster bytes multiply by the FULL chip count, tp * cp
    assert m["kv_bytes_cluster"] == (100 + 80) * 2
    json.dumps(m, allow_nan=False)
    b.memory(tp=1, kv_bytes_per_chip=80, weight_bytes_per_chip=1000, cp=4)
    assert ServingStats.merge([a, b])["cp"] is None  # heterogeneous
    # unstamped engines default cp=1, still strict-JSON
    assert ServingStats.merge([ServingStats(2)])["cp"] == 1


# ----------------------------------------------------------------------
# launch/ops under the cp mesh


def test_prewarm_under_cp_then_zero_serving_compiles(native):
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        CompileTracker,
    )

    model, params = native
    tracker = CompileTracker.install()
    eng = _engine(model, params, cp=2)
    warm = eng.prewarm()
    # the family is cp-qualified: one program per (site, shape, cp)
    assert any(s.startswith("prefill[") and s.endswith(",cp2]")
               for s in warm["by_site"]), warm["by_site"]
    before = tracker.snapshot()
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    d = CompileTracker.delta(tracker.snapshot(), before)
    assert d["n_compiled_programs"] == 0, d["by_site"]
    assert all(r.status == "done" for r in reqs)
    eng.close()


def test_chaos_event_counts_cp_invariant(native):
    """The chaos clock ticks in the HOST control loop — sharding the
    sequence axis must not move a single event."""
    model, params = native
    counts = {}
    for cp in (1, 2, 4):
        inj = FaultInjector(FaultPlan(faults=()))
        eng = _engine(model, params, cp=cp, chaos=inj)
        for p in PROMPTS:
            eng.submit(p, max_new=6)
        eng.run()
        eng.close()
        counts[cp] = (inj.events("serving-admit"),
                      inj.events("serving-step"))
    assert counts[1] == counts[2] == counts[4], counts
    assert counts[1][0] >= len(PROMPTS) and counts[1][1] > 0


def test_ring_hop_spans_carry_grouped_comm_bytes(native):
    from distributed_tensorflow_ibm_mnist_tpu.utils.flops import (
        ring_hop_bytes,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

    model, params = native
    tr = Tracer()
    eng = _engine(model, params, cp=2, tracer=tr)
    reqs = [eng.submit(p, max_new=3) for p in PROMPTS[:2]]
    eng.run()
    eng.close()
    assert all(r.status == "done" for r in reqs)
    hops = [e for e in tr.events() if e["name"] == "ring_hop"]
    # cp-1 = 1 hop per dense prefill, one prefill per request
    assert len(hops) == 2
    want = ring_hop_bytes(16 // 2, KW["heads"], KW["dim"] // KW["heads"],
                          dtype_bytes=4, depth=KW["depth"])
    for h in hops:
        assert h["args"]["comm_bytes"] == want
        assert h["args"]["timing"] == "uniform-slice"
        assert h["cat"] == "serving"


# ----------------------------------------------------------------------
# refusals: every cp>1 precondition with an actionable error


def test_cp_validation_refusals(native):
    model, params = native

    def build(**kw):
        return InferenceEngine(
            model, params, slots=2, max_len=MAX_LEN,
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,)), **kw)

    with pytest.raises(ValueError, match="cp"):
        build(cp=0)
    with pytest.raises(ValueError, match="kv_page_size"):
        build(cp=2)  # dense layout cannot shard the sequence axis
    with pytest.raises(ValueError, match="max_len"):
        build(cp=3, kv_page_size=8)  # 32 % 3 != 0
    with pytest.raises(ValueError, match="kv_pages"):
        build(cp=2, kv_page_size=8, kv_pages=9)  # explicit, indivisible
    # ring prefill owns the attn_fn seat — a model already carrying one
    # refuses cp>1 instead of silently dropping its kernel
    from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import (
        vanilla_attention,
    )

    model_fn = model.clone(attn_fn=vanilla_attention)
    with pytest.raises(ValueError, match="attn_fn"):
        InferenceEngine(
            model_fn, params, slots=2, max_len=MAX_LEN, cp=2,
            kv_page_size=8,
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,)))

"""Disaggregated prefill/decode serving (ISSUE 16).

The decisive properties of the role-typed tier:

* PARITY — a prefill(2)+decode tier produces token-identical greedy
  output to one monolithic paged engine: packaging a prefill into a
  :class:`HandoffPacket`, installing it page-by-page on the decode
  replica, and picking the first token from the handed-off logits row is
  invisible in the tokens.
* ROLE SEPARATION — prefill replicas generate ZERO tokens (the pick
  runs decode-side), decode replicas run ZERO prefill programs
  (``prewarm()["by_site"]`` pins the per-role program family), and a
  decode-role engine refuses direct submissions outright.
* EXACTLY-ONCE — a ``kv-handoff`` chaos hit releases the packet's hold
  and re-dispatches through a fresh prefill; a DOUBLE failover (a
  prefill replica dies with queued work, then a decode replica dies with
  occupied slots) still retires every request ``done`` with identical
  tokens, each streamed token delivered exactly once across attempts
  (the delivered high-water mark suppresses replayed prefixes).
* ROLLUP — ``ServingStats`` records carry their engine's ``role``, the
  router rollup groups ``per_role`` sub-rollups (decode owns the
  user-visible percentiles, prefill owns work that never retires
  locally), and everything stays strict-JSON; ``cat="handoff"`` spans
  roll up into trace_report's per-request ``handoff_ms`` column.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    Router,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)

PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6], [9, 1], [3, 3, 3, 3]]


def _model_and_params(seed=0):
    model = get_model("causal_lm", **KW)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _factory(model, params, roles, slots=2, **kw):
    def make_engine(tid, index):
        return InferenceEngine(
            model, params, slots=slots, max_len=16, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=16),
            trace_tid=tid, role=roles[index], **kw)
    return make_engine


def _reference(model, params, prompts=PROMPTS, max_new=6):
    eng = InferenceEngine(model, params, slots=2, max_len=16,
                          kv_page_size=4,
                          scheduler=FIFOScheduler(max_len=16, buckets=(8,)))
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.run()
    eng.close()
    return [list(r.generated) for r in reqs]


# ----------------------------------------------------------------------
# parity + role separation


def test_disagg_parity_and_role_separation():
    """prefill+decode tier == one monolithic paged engine, token for
    token; every request hands off exactly once; the per-role rollup
    shows zero tokens generated prefill-side."""
    model, params = _model_and_params()
    want = _reference(model, params)
    roles = ["prefill", "decode"]
    r = Router(_factory(model, params, roles), 2, roles=roles)
    rrs = [r.submit(p, max_new=6) for p in PROMPTS]
    r.run_until_done(max_steps=500)
    assert [list(rr.generated) for rr in rrs] == want
    assert all(rr.status == "done" for rr in rrs)
    assert r.handoffs == len(PROMPTS)
    assert r.handoff_faults == 0
    summ = r.summary()
    # strict JSON (None, never NaN) all the way down
    json.dumps(summ, allow_nan=False)
    per_role = summ["per_role"]
    assert set(per_role) == {"prefill", "decode"}
    assert per_role["prefill"]["tokens_generated"] == 0
    assert per_role["decode"]["tokens_generated"] == sum(
        len(t) for t in want)
    # per-engine records carry their role
    roles_seen = {rec["role"] for rec in summ["per_engine"]}
    assert roles_seen == {"prefill", "decode"}
    r.close()


def test_per_role_prewarm_census():
    """The per-role program family: a decode replica compiles ZERO
    prefill/extend/insert programs, a prefill replica ZERO pick/window
    programs — the disaggregation claim the compile census pins.  A
    UNIQUE model width keeps this test's compiles out of the process
    jit cache other tests warm (``by_site`` reports compile DELTAS)."""
    model = get_model("causal_lm", **{**KW, "dim": 48, "num_classes": 17})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    roles = ["prefill", "decode"]
    r = Router(_factory(model, params, roles), 2, roles=roles)
    warm = r.prewarm()
    pre = set(warm["replicas"][0]["by_site"])
    dec = set(warm["replicas"][1]["by_site"])
    assert not any(s.startswith(("first_pick", "decode_window[",
                                 "verify_window[")) for s in pre)
    assert any(s.startswith("prefill[") for s in pre)
    assert "handoff_gather" in pre
    assert not any(s.startswith(("prefill[", "extend[", "slot_insert"))
                   for s in dec)
    assert any(s.startswith("decode_window[") for s in dec)
    assert "first_pick" in dec and "handoff_install" in dec
    r.close()


def test_role_validation_and_decode_submit_refusal():
    model, params = _model_and_params()
    # a decode-role engine takes no direct submissions
    eng = InferenceEngine(
        model, params, slots=2, max_len=16, kv_page_size=4,
        scheduler=FIFOScheduler(max_len=16, buckets=(8,)), role="decode")
    with pytest.raises(RuntimeError, match="decode-role"):
        eng.submit([1, 2], max_new=4)
    eng.close()
    # disaggregated roles require the paged cache
    with pytest.raises(ValueError, match="kv_page_size"):
        InferenceEngine(model, params, slots=2, max_len=16,
                        scheduler=FIFOScheduler(max_len=16, buckets=(8,)),
                        role="prefill")
    # a tier needs both prefill and decode capacity
    roles = ["decode", "decode"]
    with pytest.raises(ValueError, match="prefill"):
        Router(_factory(model, params, roles), 2, roles=roles)
    # roles list must match the replica count
    with pytest.raises(ValueError, match="roles"):
        Router(_factory(model, params, ["prefill", "decode"]), 2,
               roles=["prefill"])


# ----------------------------------------------------------------------
# chaos + double failover, exactly-once


def test_kv_handoff_chaos_releases_and_redispatches_exactly_once():
    """A ``kv-handoff`` chaos hit drops the packet in flight: the router
    releases the hold, re-dispatches through a fresh prefill, and the
    wave still finishes token-identical with exactly-once streams."""
    model, params = _model_and_params()
    want = _reference(model, params)
    inj = FaultInjector(FaultPlan(seed=1, faults=(
        FaultSpec(site="kv-handoff", at=(0,)),)))
    streams: dict[int, list[int]] = {}
    roles = ["prefill", "decode"]
    r = Router(_factory(model, params, roles), 2, roles=roles, chaos=inj)
    rrs = [r.submit(p, max_new=6,
                    callback=lambda rr, tok: streams.setdefault(
                        rr.id, []).append(int(tok)))
           for p in PROMPTS]
    r.run_until_done(max_steps=500)
    assert [list(rr.generated) for rr in rrs] == want
    assert all(rr.status == "done" for rr in rrs)
    assert r.handoff_faults == 1
    assert sum(rr.redispatches for rr in rrs) == 1
    for rr in rrs:
        assert streams.get(rr.id, []) == list(rr.generated)
    r.close()


def test_double_failover_prefill_then_decode_exactly_once():
    """A prefill replica dies with queued admissions, then a decode
    replica dies with occupied slots: both casualties re-dispatch (full
    re-prefill, fresh handoff), every request retires ``done`` with
    identical tokens, and the delivered high-water mark keeps each
    stream exactly-once across all attempts."""
    model, params = _model_and_params()
    want = _reference(model, params)
    roles = ["prefill", "prefill", "decode", "decode"]
    streams: dict[int, list[int]] = {}
    r = Router(_factory(model, params, roles), 4, roles=roles)
    rrs = [r.submit(p, max_new=6,
                    callback=lambda rr, tok: streams.setdefault(
                        rr.id, []).append(int(tok)))
           for p in PROMPTS]
    # kill a prefill replica while its queue holds admissions
    dead_p = next(rep for rep in r.replicas
                  if rep.role == "prefill" and len(rep.engine.scheduler))
    r._fail_replica(dead_p, RuntimeError("induced prefill kill"))
    r.step()
    # now kill a decode replica holding live decodes
    dead_d = next(rep for rep in r.replicas
                  if rep.role == "decode" and rep.alive
                  and rep.engine.occupied)
    r._fail_replica(dead_d, RuntimeError("induced decode kill"))
    r.run_until_done(max_steps=500)
    assert [list(rr.generated) for rr in rrs] == want
    assert all(rr.status == "done" for rr in rrs)
    assert r.failovers == 2
    assert sum(rr.redispatches for rr in rrs) >= 2
    for rr in rrs:
        assert streams.get(rr.id, []) == list(rr.generated)
    summ = r.summary()
    assert summ["replicas_failed"] == 2 and summ["failovers"] == 2
    assert summ["n_engine_fault"] >= 2
    json.dumps(summ, allow_nan=False)
    r.close()


# ----------------------------------------------------------------------
# tracing rollup


def test_handoff_trace_rollup(tmp_path):
    """Handoff gather/install land ``cat="handoff"`` spans; the exported
    trace validates and trace_report rolls them up into per-request
    ``handoff_ms`` with page counts."""
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        Tracer,
        validate_trace,
    )

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import trace_report

    model, params = _model_and_params()
    tracer = Tracer()
    roles = ["prefill", "decode"]
    r = Router(_factory(model, params, roles, tracer=tracer), 2,
               roles=roles, tracer=tracer)
    rrs = [r.submit(p, max_new=4) for p in PROMPTS[:3]]
    r.run_until_done(max_steps=500)
    assert all(rr.status == "done" for rr in rrs)
    r.close()
    path = tmp_path / "trace.json"
    tracer.export_trace(str(path))
    assert validate_trace(str(path)) == []

    report = trace_report.analyze(json.loads(path.read_text()))
    names = {row["phase"] for row in report["phases"]}
    assert {"handoff/gather", "handoff/install"} <= names
    rolled = [row for row in report["requests"] if "handoff" in row]
    assert rolled, "no request rolled up handoff spans"
    assert any(row["handoff"]["pages"] > 0 for row in rolled)
    for row in rolled:
        assert row["handoff_ms"] >= 0.0
        assert row["handoff"]["dedup_pages"] <= row["handoff"]["pages"]

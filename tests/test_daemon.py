"""Daemonized serving tier (serving/daemon.py, serving/policies.py).

The decisive properties (ISSUE 15):

* PARITY + LIFECYCLE — tokens through the daemon's thread stack (pumps,
  dispatcher, delivery) are identical to one fault-free engine; a clean
  ``drain()`` + ``close()`` leaves ``tracer.open_spans == 0`` and every
  KV pool at refcount zero.
* CONSERVATION under concurrency — N producer threads hammering
  ``submit()`` against a small ``max_queue`` with deadline lapses mixed
  in: submitted == done + cancelled + failed exactly, rejections raised
  at submit and never counted as submitted, and every request's stream
  (callback order, ``stream()`` order, ``tokens``) is its final answer
  in order, exactly once.
* FAILOVER — a pump killed (``daemon-pump`` raise) or wedged
  (``daemon-pump`` wedge + the watchdog's external liveness check) mid
  wave: zero drops, exactly-once streams, token parity.
* CHAOS DETERMINISM — the same ``FaultPlan`` run twice against the
  daemonized tier (threads and all) fires at identical per-site event
  indices and yields token-identical non-poisoned outputs.
* POLICIES — priority classes drain high-before-low; the deadline
  policy admits everything cold, sheds ``SLOUnmeetable`` once its EMA
  says the TTFT SLO is unmeetable.
* THREAD-SAFE TELEMETRY — ServingStats / MetricsRegistry / Telemetry
  hammered from many threads lose no increments and never tear.
"""

import json
import os
import pathlib
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    DaemonRequest,
    DeadlineAwarePolicy,
    FIFOScheduler,
    InferenceEngine,
    PriorityPolicy,
    QueueFull,
    Router,
    ServingDaemon,
    ServingStats,
    SLOUnmeetable,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import (
    MetricsRegistry,
    Telemetry,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)

PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6], [9, 1], [3, 3, 3, 3]]

WAIT_S = 120.0   # per-request terminal wait: generous, never load-bearing


@pytest.fixture(scope="module")
def model_and_params():
    model = get_model("causal_lm", **KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _factory(model, params, **kw):
    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=16,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=16),
            trace_tid=tid, **kw)
    return make_engine


def _reference(model, params, prompts=PROMPTS, max_new=6):
    eng = InferenceEngine(model, params, slots=2, max_len=16,
                          scheduler=FIFOScheduler(max_len=16, buckets=(8,)))
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.run()
    eng.close()
    return [list(r.generated) for r in reqs]


def _pools_refcount_zero(router):
    """Every live engine's KV pool back at refcount zero: any page still
    allocated is owned by the radix cache's trie with every node ref 0
    (retained zero-ref prefixes are the cache working as designed)."""
    for rep in router.replicas:
        if not rep.alive:
            continue
        pool = getattr(rep.engine, "_pool", None)
        if pool is None:
            continue
        radix = getattr(rep.engine, "_radix", None)
        if radix is None:
            if pool.allocated != 0:
                return False
            continue
        stack = [radix.root]
        while stack:
            node = stack.pop()
            if node.ref != 0:
                return False
            stack.extend(node.children.values())
        if pool.allocated != radix.n_blocks:
            return False
    return True


def _drain_stream(daemon, dr):
    """Consume dr's event queue after the fact (terminal already set):
    the token order stream() would have yielded live."""
    out = []
    for tok in daemon.stream(dr, timeout=5.0):
        out.append(tok)
    return out


# ----------------------------------------------------------------------
# parity + lifecycle


def test_daemon_parity_streams_and_clean_drain(model_and_params):
    """Greedy decode through the full thread stack == one fault-free
    engine; callbacks/stream()/tokens agree; drain leaves open_spans == 0
    and the paged KV pools at refcount zero; conservation exact."""
    model, params = model_and_params
    want = _reference(model, params)
    tracer = Tracer()
    router = Router(_factory(model, params, kv_page_size=4), 2,
                    tracer=tracer)
    d = ServingDaemon(router, liveness_timeout_s=60.0)
    cb_order: dict[int, list[int]] = {}
    with d:
        drs = []
        for p in PROMPTS:
            got: list[int] = []
            dr = d.submit(p, 6,
                          callback=lambda dr, tok, got=got: got.append(tok))
            cb_order[dr.id] = got
            drs.append(dr)
        assert all(dr.wait(WAIT_S) for dr in drs)
        assert [dr.tokens for dr in drs] == want
        assert all(dr.status == "done" and dr.error is None for dr in drs)
        # exactly-once, in order, on every surface: delivery callback,
        # the stream() event feed, and the router's own generated list
        assert [cb_order[dr.id] for dr in drs] == want
        assert [_drain_stream(d, dr) for dr in drs] == want
        assert [list(dr.rr.generated) for dr in drs] == want
        cons = d.conservation()
        assert cons["conserved"]
        assert cons["submitted"] == cons["done"] == len(PROMPTS)
        assert cons["outstanding"] == cons["rejected"] == 0
        assert d.drain(timeout=60.0)
        # drained tier: admission refused, nothing left in flight
        with pytest.raises(RuntimeError):
            d.submit([1, 2], 2)
        assert _pools_refcount_zero(router)
    assert tracer.open_spans == 0
    with pytest.raises(RuntimeError):
        d.submit([1, 2], 2)
    d.close()   # idempotent


def test_daemon_close_cancels_queued_work(model_and_params):
    """close() without a drain settles every queued request: terminal
    ``cancelled``, end event delivered, conservation still exact."""
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    d = ServingDaemon(router, max_queue=4)   # never started: all queued
    drs = [d.submit(p, 4) for p in PROMPTS[:3]]
    d.close()
    assert all(dr.wait(5.0) for dr in drs)
    assert all(dr.status == "cancelled" for dr in drs)
    cons = d.conservation()
    assert cons["conserved"]
    assert cons["submitted"] == cons["cancelled"] == 3


# ----------------------------------------------------------------------
# backpressure + policies


def test_daemon_queue_full_at_admission_bound(model_and_params):
    """The admission bound is decided atomically at submit: the caller
    over the bound gets QueueFull, counted rejected, never submitted."""
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    d = ServingDaemon(router, max_queue=2)   # not started: queue only fills
    d.submit([1, 2], 2)
    d.submit([3, 4], 2)
    with pytest.raises(QueueFull):
        d.submit([5, 6], 2)
    cons = d.conservation()
    assert cons["rejected"] == 1 and cons["submitted"] == 2
    d.close()
    assert d.conservation()["conserved"]


def test_priority_policy_drains_high_before_low(model_and_params):
    """Requests heaped before start dispatch strictly high-priority
    first, FIFO within a class — visible in router submit order."""
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    d = ServingDaemon(router, policy=PriorityPolicy())
    prios = [0, 5, 1, 5, 0, 3]
    drs = [d.submit(p, 4, priority=pr) for p, pr in zip(PROMPTS, prios)]
    d.start()
    assert all(dr.wait(WAIT_S) for dr in drs)
    assert all(dr.status == "done" for dr in drs)
    # router.requests is dispatch order; map each back to its daemon
    # request via the rr handle
    by_rr = {id(dr.rr): dr for dr in drs}
    dispatched = [by_rr[id(rr)] for rr in router.requests]
    want = sorted(drs, key=lambda dr: (-dr.priority, dr.id))
    assert [dr.id for dr in dispatched] == [dr.id for dr in want]
    d.close()


def test_deadline_policy_predicts_and_sheds():
    """Unit math: cold start admits everything; after feedback the EMA
    predicts queue wait and sheds unmeetable TTFT SLOs as SLOUnmeetable
    (a QueueFull subclass — existing backpressure handlers shed it)."""
    pol = DeadlineAwarePolicy(alpha=0.5, concurrency=2, slack=1.0)

    def req(rid, ttft):
        return DaemonRequest(rid, [1], 1, deadline_s=None, submit_t=0.0,
                             callback=None, ttft_slo_s=ttft)

    assert pol.predicted_wait_s(10) is None
    pol.admit(req(0, 0.001), queued=100)      # cold: no basis to shed
    pol.note_first_token(0.4)
    assert pol.ema_wait_s == pytest.approx(0.4)
    pol.note_first_token(0.2)                 # EMA folds feedback in
    assert pol.ema_wait_s == pytest.approx(0.3)
    assert pol.predicted_wait_s(4) == pytest.approx(0.3 * (1 + 4 / 2))
    pol.admit(req(1, 1.0), queued=4)          # 0.9 predicted <= 1.0 SLO
    with pytest.raises(SLOUnmeetable):
        pol.admit(req(2, 0.5), queued=4)      # 0.9 predicted > 0.5 SLO
    pol.admit(req(3, None), queued=4)         # no TTFT SLO: never shed
    assert pol.shed == 1 and pol.observations == 2
    assert isinstance(SLOUnmeetable("x"), QueueFull)


def test_daemon_counts_policy_shed_as_rejected(model_and_params):
    """A policy shed at submit() surfaces to the caller and lands in the
    rejected counter — never in submitted (conservation's outer edge)."""
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    pol = DeadlineAwarePolicy(concurrency=1)
    pol.note_first_token(1.0)                 # trained: predicts 1s wait
    d = ServingDaemon(router, policy=pol)
    with pytest.raises(SLOUnmeetable):
        d.submit([1, 2], 2, ttft_slo_s=0.01)
    dr = d.submit([1, 2], 2)                  # no SLO: sails through
    cons = d.conservation()
    assert cons["rejected"] == 1 and cons["submitted"] == 1
    assert dr.status == "queued"
    d.close()


# ----------------------------------------------------------------------
# concurrent submit hammer (satellite: conservation under threads)


def test_concurrent_submit_hammer_conserves_and_orders(model_and_params):
    """N producer threads against a small admission bound with deadline
    lapses mixed in: every submit is accounted exactly once (submitted ==
    done + cancelled + failed; rejections raised at the caller), and
    every request's delivered stream is its final token list, in order."""
    model, params = model_and_params
    router = Router(_factory(model, params), 2)
    d = ServingDaemon(router, max_queue=8, liveness_timeout_s=60.0)
    d.start()
    n_threads, per_thread = 4, 10
    drs_lock = threading.Lock()
    drs: list = []
    rejected = [0] * n_threads

    def producer(t):
        for i in range(per_thread):
            # every 5th submit is born overdue -> cancelled in dispatch
            deadline = 0.0 if i % 5 == 4 else None
            try:
                dr = d.submit(PROMPTS[(t + i) % len(PROMPTS)], 3,
                              deadline_s=deadline)
            except QueueFull:
                rejected[t] += 1
                continue
            with drs_lock:
                drs.append(dr)

    threads = [threading.Thread(target=producer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(dr.wait(WAIT_S) for dr in drs)
    assert d.drain(timeout=60.0)
    cons = d.conservation()
    d.close()

    assert cons["conserved"]
    assert cons["submitted"] == len(drs)
    assert cons["rejected"] == sum(rejected)
    assert cons["submitted"] + cons["rejected"] == n_threads * per_thread
    by_status: dict[str, int] = {}
    for dr in drs:
        by_status[dr.status] = by_status.get(dr.status, 0) + 1
    assert by_status.get("done", 0) == cons["done"] > 0
    assert by_status.get("cancelled", 0) == cons["cancelled"]
    assert by_status.get("failed", 0) == cons["failed"] == 0
    # per-request order and exactly-once: the delivered stream IS the
    # final token list, and matches the router's record where dispatched
    for dr in drs:
        assert _drain_stream(d, dr) == dr.tokens
        if dr.status == "done":
            assert dr.tokens == list(dr.rr.generated)
            assert len(dr.tokens) == 3
        elif dr.rr is None:
            assert dr.tokens == []


# ----------------------------------------------------------------------
# failover: pump killed, pump wedged


def test_pump_kill_failover_zero_drops_exactly_once(model_and_params):
    """daemon-pump chaos kills one of two pumps mid-wave: the survivor
    absorbs the harvest, every request still retires done with reference
    tokens, streams stay exactly-once, conservation exact."""
    model, params = model_and_params
    want = _reference(model, params)
    inj = FaultInjector(FaultPlan(seed=3, faults=(
        FaultSpec(site="daemon-pump", kind="raise", at=(0,)),)))
    router = Router(_factory(model, params), 2, chaos=inj)
    d = ServingDaemon(router, liveness_timeout_s=60.0)
    drs = [d.submit(p, 6) for p in PROMPTS]   # work waiting before pumps
    d.start()
    assert all(dr.wait(WAIT_S) for dr in drs)
    assert all(dr.status == "done" for dr in drs)        # zero drops
    assert [dr.tokens for dr in drs] == want             # parity
    assert [list(dr.rr.generated) for dr in drs] == want  # exactly-once
    assert router.failovers == 1
    assert d.counters["pump_faults"] == 1
    assert [(f.site, f.event, f.kind) for f in inj.fired] == [
        ("daemon-pump", 0, "raise")]
    assert d.drain(timeout=60.0)
    cons = d.conservation()
    d.close()
    assert cons["conserved"] and cons["done"] == len(PROMPTS)


def test_pump_wedge_watchdog_failover(model_and_params):
    """daemon-pump kind="wedge" parks a pump with its heartbeat frozen —
    ``step()`` never raises, so only the watchdog's EXTERNAL liveness
    check can notice.  It must fail the replica over and the survivor
    must finish the wave with zero drops."""
    model, params = model_and_params
    want = _reference(model, params)
    inj = FaultInjector(FaultPlan(seed=4, faults=(
        FaultSpec(site="daemon-pump", kind="wedge", at=(0,)),)))
    tracer = Tracer()
    router = Router(_factory(model, params), 2, chaos=inj, tracer=tracer)
    router.prewarm()   # compiles out of the liveness window
    d = ServingDaemon(router, liveness_timeout_s=1.5,
                      watchdog_interval_s=0.05)
    drs = [d.submit(p, 6) for p in PROMPTS]
    d.start()
    assert all(dr.wait(WAIT_S) for dr in drs)
    assert all(dr.status == "done" for dr in drs)
    assert [dr.tokens for dr in drs] == want
    assert router.failovers == 1
    assert d.counters["pump_wedges"] == 1
    wedged = [f for f in inj.fired if f.site == "daemon-pump"]
    assert [(f.event, f.kind) for f in wedged] == [(0, "wedge")]
    assert d.drain(timeout=60.0)
    cons = d.conservation()
    d.close()
    assert cons["conserved"] and cons["done"] == len(PROMPTS)
    assert tracer.open_spans == 0


# ----------------------------------------------------------------------
# chaos determinism under threads (ISSUE 15 acceptance)


def _determinism_run(model, params, n_replicas, plan):
    """One daemonized run under ``plan`` with all work submitted before
    the threads start; returns the chaos fired log and every request's
    terminal (status, tokens)."""
    inj = FaultInjector(plan)
    router = Router(_factory(model, params, chaos=inj), n_replicas,
                    chaos=inj)
    d = ServingDaemon(router, liveness_timeout_s=60.0)
    drs = [d.submit(p, 6) for p in PROMPTS]
    d.start()
    assert all(dr.wait(WAIT_S) for dr in drs)
    assert d.drain(timeout=60.0)
    d.close()
    fired = [(f.site, f.event, f.kind, f.spec_idx) for f in inj.fired]
    outputs = [(dr.status, tuple(dr.tokens)) for dr in drs]
    return fired, outputs, inj.events("daemon-pump")


def test_chaos_determinism_repeated_run(model_and_params):
    """The replayability pin: the same FaultPlan run twice against the
    daemonized tier — pump/dispatcher/delivery threads interleaving
    freely — fires at identical per-site event indices and yields
    token-identical non-poisoned outputs."""
    model, params = model_and_params

    # (a) single replica, a poisoned admission mid-wave: the per-site
    # FIFO admission order pins exactly WHICH request dies
    plan = FaultPlan(seed=7, faults=(
        FaultSpec(site="serving-admit", kind="raise", at=(2,)),))
    fired1, out1, pump_events1 = _determinism_run(model, params, 1, plan)
    fired2, out2, pump_events2 = _determinism_run(model, params, 1, plan)
    assert fired1 == fired2 == [("serving-admit", 2, "raise", 0)]
    assert out1 == out2
    assert pump_events1 == pump_events2 == 1   # one pump, consulted once
    statuses = [s for s, _ in out1]
    assert statuses.count("failed") == 1 and statuses.count("done") == 5
    assert out1[2][0] == "failed"              # admission order == submit

    # (b) two replicas, a pump killed: WHICH pump loses the race for
    # event 0 is scheduling-dependent, but the per-site event log and
    # the token outputs are interleaving-invariant
    plan = FaultPlan(seed=8, faults=(
        FaultSpec(site="daemon-pump", kind="raise", at=(0,)),))
    fired1, out1, _ = _determinism_run(model, params, 2, plan)
    fired2, out2, _ = _determinism_run(model, params, 2, plan)
    assert fired1 == fired2 == [("daemon-pump", 0, "raise", 0)]
    assert out1 == out2
    assert all(s == "done" for s, _ in out1)


# ----------------------------------------------------------------------
# thread-safe stats/telemetry (satellite: no torn counters)


def test_serving_stats_concurrent_hammer_exact_counts():
    """Many threads mutating one ServingStats while merge/summary run
    concurrently: no increment lost, no exception, merged counters sum
    exactly (the pre-lock implementation tore under this load)."""
    a, b = ServingStats(slots=2), ServingStats(slots=2)
    n_threads, iters = 8, 300
    stop = threading.Event()
    reader_errors: list = []

    def mutate(rec):
        for i in range(iters):
            rec.tick(occupied=1, dt=0.001, decoded=True)
            rec.prefix(hit=i % 2 == 0)
            rec.spec(drafted=2, accepted=1)

    def read():
        while not stop.is_set():
            try:
                a.summary()
                ServingStats.merge([a, b])
            except Exception as e:   # pragma: no cover - the regression
                reader_errors.append(e)
                return

    threads = ([threading.Thread(target=mutate, args=(a,))
                for _ in range(n_threads // 2)]
               + [threading.Thread(target=mutate, args=(b,))
                  for _ in range(n_threads // 2)]
               + [threading.Thread(target=read) for _ in range(2)])
    for t in threads:
        t.start()
    for t in threads[:n_threads]:
        t.join()
    stop.set()
    for t in threads[n_threads:]:
        t.join()
    assert not reader_errors
    per_rec = (n_threads // 2) * iters
    for rec in (a, b):
        s = rec.summary()
        assert s["decode_steps"] == per_rec
        assert s["prefix_hits"] + s["prefix_misses"] == per_rec
        assert s["drafted_tokens"] == 2 * per_rec
        assert s["accepted_tokens"] == per_rec
    merged = ServingStats.merge([a, b])
    assert merged["decode_steps"] == 2 * per_rec


def test_metrics_registry_concurrent_inc_is_exact():
    """Parallel inc/observe/snapshot: the counter lands on exactly
    n_threads * iters — a single lost update fails this."""
    reg = MetricsRegistry()
    n_threads, iters = 8, 500

    def work():
        for i in range(iters):
            reg.inc("hits")
            reg.observe("lat", 0.001 * (i % 7 + 1))
            reg.set_gauge("depth", i)

    readers_stop = threading.Event()

    def read():
        while not readers_stop.is_set():
            reg.snapshot()
            reg.to_prometheus()

    threads = ([threading.Thread(target=work) for _ in range(n_threads)]
               + [threading.Thread(target=read)])
    for t in threads:
        t.start()
    for t in threads[:n_threads]:
        t.join()
    readers_stop.set()
    threads[-1].join()
    snap = reg.snapshot()
    assert snap["counters"]["hits"] == n_threads * iters


def test_telemetry_maybe_sample_once_per_interval():
    """Concurrent maybe_sample() calls racing one interval boundary:
    exactly ONE caller samples (the double-checked lock), the rest see
    None — no duplicate samples, no torn sample count."""
    t = [0.0]
    tel = Telemetry(interval_s=1.0, clock=lambda: t[0])
    tel.register_source("x", lambda: {"v": 1})
    for tick in (0.0, 10.0, 20.0):
        t[0] = tick
        barrier = threading.Barrier(8)
        results: list = []
        res_lock = threading.Lock()

        def call():
            barrier.wait()
            r = tel.maybe_sample()
            with res_lock:
                results.append(r)

        threads = [threading.Thread(target=call) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert sum(r is not None for r in results) == 1
    assert tel.samples == 3
    tel.close()


# ----------------------------------------------------------------------
# the SLO bench, quick form


@pytest.mark.slow
def test_bench_slo_quick_gates():
    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu", DTM_BENCH_QUICK="1")
    out = subprocess.run(
        [sys.executable, str(repo / "scripts" / "bench_slo.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, (
        f"bench_slo quick failed rc={out.returncode}; "
        f"stderr tail: {out.stderr[-800:]!r}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "slo_daemon"
    assert rec["passed"] is True
    assert all(rec["gates"].values()), rec["gates"]

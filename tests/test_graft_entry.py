"""Driver-contract tests for __graft_entry__.py.

The driver (a) compile-checks ``entry()`` single-chip and (b) runs
``dryrun_multichip(n)`` with a virtual n-device CPU platform.  These tests pin
both contracts — including that dryrun self-arms its device count in a fresh
interpreter with NO env vars set (the axon sitecustomize pins jax_platforms at
interpreter start, so env-only arming is not enough).
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_entry_returns_jittable_forward():
    import jax

    sys.path.insert(0, str(REPO))
    try:
        import __graft_entry__ as g
    finally:
        sys.path.pop(0)
    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (args[1].shape[0], 10)


def test_dryrun_multichip_self_arms_in_clean_subprocess():
    # Strip every platform/device hint from the env: the dryrun must build
    # its own 8-device CPU mesh.
    import os

    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=str(REPO), env=env, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

"""Gradient accumulation and rematerialization semantics.

grad_accum=k over a batch must equal the single-shot step on the same batch
(mean-of-microbatch-gradients == full-batch gradient for mean losses); remat
must change memory behavior only, never numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig


def _setup(model_name="mlp", **model_kw):
    model = get_model(model_name, num_classes=10, dtype=jnp.float32, **model_kw)
    tx = optax.sgd(0.1)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, size=(32, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, size=(32,)).astype(np.int32)),
    }
    return model, tx, state, batch


@pytest.mark.parametrize("accum", [2, 4])
def test_grad_accum_matches_single_shot(accum):
    model, tx, state, batch = _setup(hidden=(32,))
    s1, m1 = jax.jit(make_train_step(model, tx))(state, batch)
    sk, mk = jax.jit(make_train_step(model, tx, grad_accum=accum))(state, batch)
    np.testing.assert_allclose(float(mk["loss"]), float(m1["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(mk["accuracy"]), float(m1["accuracy"]), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sk.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_grad_accum_batchnorm_model():
    """ResNet (BatchNorm): accum path must thread stats through microbatches."""
    model, tx, state, batch = _setup("resnet20")
    sk, mk = jax.jit(make_train_step(model, tx, grad_accum=2))(state, batch)
    assert np.isfinite(float(mk["loss"]))
    assert int(sk.step) == 1
    # stats actually updated
    a = jax.tree.leaves(state.batch_stats)[0]
    b = jax.tree.leaves(sk.batch_stats)[0]
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_grad_accum_indivisible_rejected():
    model, tx, state, batch = _setup(hidden=(32,))
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(make_train_step(model, tx, grad_accum=5))(state, batch)


def test_remat_identical_numerics():
    model, tx, state, batch = _setup(hidden=(64, 64))
    s1, m1 = jax.jit(make_train_step(model, tx))(state, batch)
    s2, m2 = jax.jit(make_train_step(model, tx, remat=True))(state, batch)
    np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_trainer_with_accum_and_remat():
    cfg = RunConfig(
        model="mlp", model_kwargs={"hidden": (32,)}, synthetic=True,
        n_train=512, n_test=128, batch_size=64, epochs=2, quiet=True,
        grad_accum=2, remat=True,
    )
    summary = Trainer(cfg).fit()
    assert summary["epochs_run"] == 2
    assert np.isfinite(summary["best_test_accuracy"])


def test_vit_flash_by_name():
    """attn='flash' via model_kwargs (config/CLI path) trains."""
    cfg = RunConfig(
        model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 1, "heads": 2, "attn": "flash"},
        synthetic=True, n_train=128, n_test=32, batch_size=64, epochs=1, quiet=True,
        eval_batch_size=32,
    )
    summary = Trainer(cfg).fit()
    assert np.isfinite(summary["best_test_accuracy"])


def test_block_remat_matches_plain():
    """block_remat=True is a pure memory/schedule change: identical step
    numerics for ResNet (BN stats included) and ViT (dropout included)."""
    import numpy as np

    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, (8, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, 8).astype(np.int32)),
    }
    for name, kw in [
        ("resnet20", {}),
        ("vit", {"patch_size": 7, "dim": 16, "depth": 2, "heads": 2, "dropout": 0.1}),
    ]:
        outs = []
        for br in (False, True):
            m = get_model(name, num_classes=10, dtype=jnp.float32, block_remat=br, **kw)
            tx = optax.sgd(1e-2)
            st = TrainState.create(
                m, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
            )
            st2, met = jax.jit(make_train_step(m, tx))(st, batch)
            outs.append((jax.device_get(st2.params), float(met["loss"])))
        assert abs(outs[0][1] - outs[1][1]) < 1e-6
        for a, b in zip(jax.tree.leaves(outs[0][0]), jax.tree.leaves(outs[1][0])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_remat_blocks_config_driven():
    """RunConfig(remat='blocks') reaches the model; non-block models reject."""
    import pytest

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    t = Trainer(RunConfig(
        model="resnet20", dataset="fashion_mnist", synthetic=True,
        n_train=64, n_test=32, batch_size=32, epochs=1, remat="blocks",
        quiet=True, eval_batch_size=32,
    ))
    assert t.model.block_remat is True
    s = t.fit()
    assert s["epochs_run"] == 1

    with pytest.raises(ValueError, match="blocks"):
        Trainer(RunConfig(model="mlp", synthetic=True, n_train=64, n_test=32,
                          batch_size=32, remat="blocks", quiet=True))

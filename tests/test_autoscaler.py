"""Recorded arrival traces (serving/traces.py) + telemetry-driven
elastic capacity (serving/autoscaler.py) — ISSUE 17.

The decisive properties:

* TRACES ARE ARTIFACTS — every generator is deterministic under its
  seed, offsets are sorted, shapes respect their clip bounds, and a
  trace survives a JSONL save/load round trip event-identical; SLOs are
  stamped at replay time (:func:`with_slos`), never baked into the
  recorded shape.
* PER-CLASS ACCOUNTING — :func:`per_class_report` splits goodput by
  traffic class and judges TTFT/TPOT SLOs end-to-end from delivered
  streams; a miss in one class never hides inside the other's average.
* ELASTIC MECHANISM — ``retire_replica`` drains before closing (zero
  drops with in-flight work), leaves the replica ``retired`` (clean
  exit, distinguishable from failures), and ``restart_replica`` brings
  it back WARM with the tier's current weights; ``add_replica`` grows
  the tier live.
* CONTROL LOOP — hysteresis streaks gate both directions, contrary
  evidence resets them, the floor/ceiling bound every decision, an
  in-flight retire freezes the loop, and policy sheds register as
  immediate up-pressure.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    ArrivalTrace,
    Autoscaler,
    FIFOScheduler,
    InferenceEngine,
    Router,
    ServingDaemon,
    TraceEvent,
    bursty_trace,
    diurnal_trace,
    heavy_tail_trace,
    per_class_report,
    poisson_trace,
    replay_trace,
    with_slos,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.replica import (
    DRAINING,
    FAILED,
    HEALTHY,
)

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)
PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6], [9, 1], [3, 3]]
WAIT_S = 120.0


@pytest.fixture(scope="module")
def model_and_params():
    model = get_model("causal_lm", **KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _factory(model, params, **kw):
    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=16, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=16),
            trace_tid=tid, **kw)
    return make_engine


# ----------------------------------------------------------------------
# traces: generators, schema, round trip


@pytest.mark.parametrize("gen,kw", [
    (poisson_trace, dict(rate_rps=5.0)),
    (bursty_trace, dict(base_rps=2.0, burst_rps=25.0,
                        burst_every_s=1.0, burst_len_s=0.25)),
    (diurnal_trace, dict(mean_rps=5.0, period_s=4.0, depth=0.8)),
    (heavy_tail_trace, dict(rate_rps=5.0, alpha=1.5)),
])
def test_generators_deterministic_sorted_bounded(gen, kw):
    a = gen(40, seed=7, **kw)
    b = gen(40, seed=7, **kw)
    c = gen(40, seed=8, **kw)
    assert a.events == b.events                      # seed-deterministic
    assert a.events != c.events                      # seed-sensitive
    assert len(a) == 40
    offs = [e.t_offset for e in a]
    assert offs == sorted(offs) and offs[0] >= 0.0
    for ev in a:
        assert 1 <= ev.prompt_len <= 8 and 1 <= ev.max_new <= 8
        assert ev.cls in ("interactive", "batch")
        assert ev.priority == (1 if ev.cls == "interactive" else 0)
        assert ev.ttft_slo_s is None                 # shape only, no SLOs
    counts = a.class_counts()
    assert counts["interactive"] + counts["batch"] == 40


def test_trace_round_trip_and_slo_stamping(tmp_path):
    trace = heavy_tail_trace(25, 4.0, seed=3)
    path = trace.save(tmp_path / "ht.jsonl")
    back = ArrivalTrace.load(path)
    assert back.name == trace.name
    assert back.events == trace.events
    stamped = with_slos(back, interactive_ttft_slo_s=0.5,
                        batch_ttft_slo_s=2.0, batch_tpot_slo_s=0.1)
    for ev in stamped:
        if ev.cls == "interactive":
            assert ev.ttft_slo_s == 0.5 and ev.tpot_slo_s is None
        else:
            assert ev.ttft_slo_s == 2.0 and ev.tpot_slo_s == 0.1
    # the recorded artifact is untouched
    assert all(e.ttft_slo_s is None for e in back)
    # corrupt header is refused, not misparsed
    bogus = tmp_path / "not_a_trace.jsonl"
    bogus.write_text('{"schema": "something-else"}\n')
    with pytest.raises(ValueError):
        ArrivalTrace.load(bogus)


def test_trace_event_validation():
    with pytest.raises(ValueError):
        TraceEvent(t_offset=-1.0, prompt_len=2, max_new=2)
    with pytest.raises(ValueError):
        TraceEvent(t_offset=0.0, prompt_len=0, max_new=2)
    with pytest.raises(ValueError):
        TraceEvent(t_offset=0.0, prompt_len=2, max_new=2, cls="bulk")
    with pytest.raises(ValueError):
        bursty_trace(5, 5.0, 2.0, seed=1, burst_every_s=1.0,
                     burst_len_s=0.5)          # burst below base
    with pytest.raises(ValueError):
        diurnal_trace(5, 5.0, seed=1, period_s=1.0, depth=1.0)
    with pytest.raises(ValueError):
        heavy_tail_trace(5, 5.0, seed=1, alpha=1.0)


class _FakeDr:
    """Just enough DaemonRequest surface for per_class_report."""

    def __init__(self, status, tokens, submit_t=0.0, first_token_t=None):
        self.status = status
        self.tokens = tokens
        self.submit_t = submit_t
        self.first_token_t = first_token_t
        self.done = status is not None
        self.rr = None


def test_per_class_report_accounting():
    ev_i = TraceEvent(t_offset=0.0, prompt_len=2, max_new=2,
                      cls="interactive", ttft_slo_s=1.0)
    ev_b = TraceEvent(t_offset=0.0, prompt_len=2, max_new=2, cls="batch")
    outcomes = [
        (ev_i, _FakeDr("done", [1, 2], first_token_t=0.5), [1, 2]),   # met
        (ev_i, _FakeDr("done", [3, 4], first_token_t=2.0), [3, 4]),   # TTFT miss
        (ev_i, None, []),                                             # rejected
        (ev_i, _FakeDr("cancelled", []), []),
        (ev_b, _FakeDr("done", [5], first_token_t=3.0), [5]),  # met: no SLO
        (ev_b, _FakeDr("done", [6, 7], first_token_t=0.1), [9, 9]),  # replayed
    ]
    rep = per_class_report(outcomes, wall_s=10.0)
    inter, batch = rep["per_class"]["interactive"], rep["per_class"]["batch"]
    assert inter["offered"] == 4 and inter["accepted"] == 3
    assert inter["rejected"] == 1 and inter["cancelled"] == 1
    assert inter["done"] == 2 and inter["slo_met"] == 1
    assert inter["goodput_rps"] == pytest.approx(0.1)
    assert batch["done"] == 2 and batch["slo_met"] == 2
    assert batch["exactly_once"] is False         # stream != final tokens
    assert inter["exactly_once"] is True          # the miss stays in batch
    assert rep["total"]["offered"] == 6
    assert rep["total"]["exactly_once"] is False


def test_replay_trace_against_live_tier(model_and_params):
    model, params = model_and_params
    router = Router(_factory(model, params), 2)
    daemon = ServingDaemon(router, max_queue=64).start()
    try:
        trace = with_slos(
            poisson_trace(10, 20.0, seed=11, prompt_len=(2, 5),
                          max_new=(2, 4)),
            interactive_ttft_slo_s=30.0, batch_ttft_slo_s=30.0)
        rep = replay_trace(daemon, trace, vocab=16, seed=1,
                           timeout_s=WAIT_S)
        tot = rep["total"]
        assert tot["offered"] == 10
        assert tot["done"] == tot["accepted"] and tot["unfinished"] == 0
        assert tot["exactly_once"] and tot["slo_met"] == tot["done"]
        assert daemon.conservation()["conserved"]
        assert daemon.drain(timeout=30.0)
    finally:
        daemon.close()


# ----------------------------------------------------------------------
# elastic mechanism: retire / add / warm restart on the live daemon


def test_retire_drains_zero_drops_then_warm_restart(model_and_params):
    model, params = model_and_params
    router = Router(_factory(model, params), 2)
    daemon = ServingDaemon(router, max_queue=64).start()
    try:
        wave = [daemon.submit(p, 6) for p in PROMPTS]
        # retire replica 1 with the wave in flight: it must finish its
        # accepted work before closing — scale-down drops nothing
        assert daemon.retire_replica(1)
        assert router.replicas[1].state in (DRAINING, FAILED)
        for dr in wave:
            assert dr.wait(timeout=WAIT_S)
            assert dr.status == "done", (dr.id, dr.status, dr.error)
        deadline = time.monotonic() + WAIT_S
        while time.monotonic() < deadline and router._retiring:
            time.sleep(0.02)
        rep = router.replicas[1]
        assert rep.state == FAILED and rep.retired and not rep.alive
        assert router.retires == 1
        # retired != failed in the books
        assert router.summary()["replicas_failed"] == 0
        assert router.summary()["replicas_retired"] == 1
        # the floor holds: the survivor cannot retire
        assert daemon.retire_replica(0) is False
        # traffic still flows on the remaining replica
        dr = daemon.submit([7, 7, 7], 4)
        assert dr.wait(timeout=WAIT_S) and dr.status == "done"
        # warm restart: same replica object, back to HEALTHY, current
        # weights stamped, and dispatchable again
        spawn_s = daemon.restart_replica(1)
        assert spawn_s >= 0.0
        assert router.replicas[1].state == HEALTHY
        assert not router.replicas[1].retired
        wave2 = [daemon.submit(p, 4) for p in PROMPTS]
        for dr in wave2:
            assert dr.wait(timeout=WAIT_S) and dr.status == "done"
        cons = daemon.conservation()
        assert cons["conserved"] and cons["failed"] == 0
        assert daemon.drain(timeout=30.0)
    finally:
        daemon.close()


def test_add_replica_grows_live_tier(model_and_params):
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    daemon = ServingDaemon(router, max_queue=64).start()
    try:
        wave = [daemon.submit(p, 4) for p in PROMPTS[:3]]
        rep = daemon.add_replica()
        assert rep.index == 1 and rep.state == HEALTHY
        assert len(router.replicas) == 2
        assert router.scale_ups == 1
        # the new replica serves: submit enough to spread across both
        wave += [daemon.submit(p, 4) for p in PROMPTS]
        for dr in wave:
            assert dr.wait(timeout=WAIT_S) and dr.status == "done"
        served = sum(r.engine.stats.summary()["n_done"]
                     for r in router.replicas if r.alive)
        assert served == len(wave)
        assert daemon.conservation()["conserved"]
        assert daemon.drain(timeout=30.0)
    finally:
        daemon.close()


# ----------------------------------------------------------------------
# control loop (stub tier: pure logic, no model)


class _StubEngine:
    def __init__(self, slots=2):
        self.slots = slots
        self.occupied = 0
        self._closed = False


class _StubReplica:
    def __init__(self, index):
        self.index = index
        self.state = HEALTHY
        self.engine = _StubEngine()
        self.retired = False
        self.spawn_s = 0.01
        self.load = 0.0

    @property
    def alive(self):
        return not self.engine._closed


class _StubPolicy:
    def __init__(self):
        self.shed = 0


class _StubDaemon:
    def __init__(self, n=2):
        class _R:
            pass
        self.router = _R()
        self.router.replicas = [_StubReplica(i) for i in range(n)]
        self.router._retiring = set()
        self._adm_cv = threading.Lock()
        self._admission = []
        self._inflight = {}
        self.policy = _StubPolicy()
        self._telemetry = None
        self.retired_calls = []
        self.added = 0

    def retire_replica(self, index):
        self.router.replicas[index].state = DRAINING
        self.router.replicas[index].engine._closed = True
        self.router.replicas[index].retired = True
        self.retired_calls.append(index)
        return True

    def restart_replica(self, index):
        rep = self.router.replicas[index]
        rep.state = HEALTHY
        rep.engine = _StubEngine()
        rep.retired = False
        return 0.005

    def add_replica(self, role="both"):
        rep = _StubReplica(len(self.router.replicas))
        self.router.replicas.append(rep)
        self.added += 1
        return rep


def test_hysteresis_streaks_and_reset():
    stub = _StubDaemon(n=2)
    asc = Autoscaler(stub, min_replicas=1, max_replicas=4,
                     hysteresis_up=3, hysteresis_down=3,
                     down_occupancy=0.5)
    stub._admission = list(range(20))     # heavy backlog: up-pressure
    assert asc.tick() is None
    assert asc.tick() is None
    # contrary evidence resets the streak
    stub._admission = []
    for rep in stub.router.replicas:
        rep.engine.occupied = rep.engine.slots      # busy: no down either
    assert asc.tick() is None
    stub._admission = list(range(20))
    assert asc.tick() is None                       # streak restarted at 1
    assert asc.tick() is None
    assert asc.tick() == "up"                       # 3 consecutive
    assert stub.added == 1
    assert asc.events[-1]["action"] == "up" and not asc.events[-1]["warm"]


def test_shed_is_immediate_up_pressure():
    stub = _StubDaemon(n=1)
    asc = Autoscaler(stub, min_replicas=1, max_replicas=2,
                     hysteresis_up=1, hysteresis_down=10)
    # no backlog at all — but the policy shed someone since last tick
    stub.policy.shed = 3
    assert asc.tick() == "up"
    assert asc.summary()["scale_ups"] == 1


def test_ceiling_floor_and_freeze_while_retiring():
    stub = _StubDaemon(n=2)
    asc = Autoscaler(stub, min_replicas=2, max_replicas=2,
                     hysteresis_up=1, hysteresis_down=1,
                     down_occupancy=0.9)
    stub._admission = list(range(50))
    assert asc.tick() is None            # at ceiling: up vetoed
    stub._admission = []
    assert asc.tick() is None            # at floor: down vetoed
    assert stub.added == 0 and stub.retired_calls == []
    # a retire in flight freezes every decision
    stub.router._retiring.add(1)
    stub._admission = list(range(50))
    assert asc.tick() is None
    stub.router._retiring.clear()
    assert asc.tick() == "up" or stub.added == 0  # unfrozen: ceiling still vetoes


def test_scale_down_prefers_least_loaded_and_warm_up_prefers_retired():
    stub = _StubDaemon(n=3)
    stub.router.replicas[0].load = 0.5
    stub.router.replicas[1].load = 3.0
    stub.router.replicas[2].load = 0.5
    asc = Autoscaler(stub, min_replicas=1, max_replicas=3,
                     hysteresis_up=1, hysteresis_down=1,
                     down_occupancy=0.9)
    assert asc.tick() == "down"
    # equal-load tie broke toward the higher index: replica 0 survives
    assert stub.retired_calls == [2]
    # now scale up: the retired replica restarts WARM instead of growing
    stub.router._retiring.clear()
    stub._admission = list(range(50))
    assert asc.tick() == "up"
    assert stub.added == 0                       # no new replica built
    assert stub.router.replicas[2].state == HEALTHY
    ev = asc.events[-1]
    assert ev["action"] == "up" and ev["warm"] and ev["replica"] == 2
    assert asc.chip_seconds() > 0.0
    s = asc.summary()
    assert s["scale_ups"] == 1 and s["scale_downs"] == 1
    assert s["warm_ups"] == 1 and len(s["spawn_s"]) == 1


def test_autoscaler_threaded_runner_against_live_tier(model_and_params):
    model, params = model_and_params
    router = Router(_factory(model, params), 1)
    daemon = ServingDaemon(router, max_queue=64).start()
    asc = Autoscaler(daemon, min_replicas=1, max_replicas=2,
                     hysteresis_up=1, hysteresis_down=1000,
                     up_backlog_per_slot=1.5, interval_s=0.02)
    try:
        with asc:
            wave = [daemon.submit(p, 6) for p in PROMPTS * 3]
            for dr in wave:
                assert dr.wait(timeout=WAIT_S)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not asc.events:
                time.sleep(0.02)
        assert all(dr.status == "done" for dr in wave)
        assert daemon.conservation()["conserved"]
        assert daemon.drain(timeout=30.0)
    finally:
        asc.stop()
        daemon.close()


def test_autoscaler_validation():
    stub = _StubDaemon()
    with pytest.raises(ValueError):
        Autoscaler(stub, min_replicas=0)
    with pytest.raises(ValueError):
        Autoscaler(stub, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        Autoscaler(stub, hysteresis_up=0)

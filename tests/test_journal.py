"""Crash durability (serving/journal.py) — the write-ahead request
journal and whole-process recovery.

The decisive properties (ISSUE 18):

* WAL ORDER — ``admitted`` is on disk before ``submit()`` returns; a
  raising append fails the submit (no ack without the WAL behind it);
  ``delivered`` never overstates what the client received.
* TORN-TAIL TOLERANCE — truncated final record, bit-flipped checksum,
  empty segment, missing segment: the scan drops exactly what cannot be
  trusted (``records_dropped``), flags the crash signature
  (``torn_tail``), surfaces gaps, and recovery proceeds on the rest.
* EXACTLY-ONCE ACROSS THE CRASH — ``recover()`` re-submits every
  incomplete request with ``resume_from=<delivered high-water>``; the
  deterministic stream (PR 13) re-derives identical tokens, so the
  stitched transcript (delivered prefix + replayed suffix) is
  token-identical to an uncrashed reference, no gaps, no duplicates.
* CHAOS — the ``journal-write`` site's torn/corrupt/io kinds produce
  exactly the on-disk damage the scan is built for.
"""

import os
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    JournalWriteError,
    RequestJournal,
    Router,
    SamplingParams,
    ServingDaemon,
    recover,
    scan_journal,
    transcript_digest,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.daemon import DaemonRequest
from distributed_tensorflow_ibm_mnist_tpu.serving.journal import (
    _encode,
    _segment_name,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)

KW = dict(num_classes=16, dim=32, depth=1, heads=2, dtype=jnp.float32)
PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
WAIT_S = 120.0


@pytest.fixture(scope="module")
def model_and_params():
    model = get_model("causal_lm", **KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _factory(model, params):
    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=16,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=16),
            trace_tid=tid)
    return make_engine


def _reference(model, params, prompts=PROMPTS, max_new=6, sampling=None):
    eng = InferenceEngine(model, params, slots=2, max_len=16,
                          scheduler=FIFOScheduler(max_len=16, buckets=(8,)))
    reqs = [eng.submit(p, max_new=max_new, sampling=sampling)
            for p in prompts]
    eng.run()
    eng.close()
    return [list(r.generated) for r in reqs]


def _fake_dr(rid, prompt=(1, 2, 3), max_new=4, **kw):
    """A DaemonRequest the journal can serialize without a live tier."""
    dr = DaemonRequest(rid, list(prompt), max_new,
                       deadline_s=kw.pop("deadline_s", 30.0),
                       submit_t=0.0, callback=None, **kw)
    dr.fingerprint = "00" * 8
    return dr


# ----------------------------------------------------------------------
# write side


def test_journal_roundtrip_rotation_and_fresh_segments(tmp_path):
    """Records round-trip through the checksummed segment files; tiny
    ``segment_bytes`` forces rotation; a second writer over the same
    directory never reopens an existing segment."""
    d = str(tmp_path / "j")
    with RequestJournal(d, fsync_policy="never", segment_bytes=200) as j:
        for i in range(4):
            j.admitted(_fake_dr(i))
        j.delivered(0, 2)
        j.delivered(0, 3)           # high-water moves forward
        j.retired(0, "done", None)
        j.retired(1, "failed", "boom")
    st = j.stats()
    assert st["records"] == 8
    assert st["by_type"] == {"admitted": 4, "delivered": 2, "retired": 2}
    assert st["rotations"] >= 2     # 200-byte segments can't hold it all
    assert st["errors"] == 0

    scan = scan_journal(d)
    assert scan.records == 8
    assert scan.records_dropped == 0 and not scan.torn_tail
    assert scan.segment_gaps == [] and scan.orphan_records == 0
    assert sorted(scan.requests) == [0, 1, 2, 3]
    assert scan.requests[0] == {"meta": scan.requests[0]["meta"],
                                "delivered": 3, "retired": "done"}
    assert scan.requests[1]["retired"] == "failed"
    assert [s["meta"]["id"] for s in scan.incomplete()] == [2, 3]
    rep = scan.report()
    assert rep["requests"] == 4 and rep["retired"] == 2
    assert rep["incomplete"] == 2

    # a fresh writer starts PAST every existing segment
    first_segments = set(scan.segments)
    with RequestJournal(d, fsync_policy="never") as j2:
        j2.retired(2, "cancelled", None)
    scan2 = scan_journal(d)
    new = set(scan2.segments) - first_segments
    assert len(new) == 1            # one new segment, none reopened
    assert scan2.requests[2]["retired"] == "cancelled"

    # meta preserves the full identity recovery needs
    meta = scan2.requests[3]["meta"]
    assert meta["prompt"] == [1, 2, 3] and meta["max_new"] == 4
    assert meta["fp"] == "00" * 8 and "wall_t" in meta


def test_journal_fsync_policies(tmp_path):
    """Policy validation + the fsync ledger: ``always`` pays one fsync
    per append, ``never`` only the final close-fsync."""
    with pytest.raises(ValueError):
        RequestJournal(str(tmp_path / "x"), fsync_policy="sometimes")
    with pytest.raises(ValueError):
        RequestJournal(str(tmp_path / "x"), fsync_interval_s=0)
    with pytest.raises(ValueError):
        RequestJournal(str(tmp_path / "x"), segment_bytes=0)

    ja = RequestJournal(str(tmp_path / "a"), fsync_policy="always")
    for i in range(5):
        ja.delivered(0, i)
    ja.close()
    assert ja.stats()["fsyncs"] >= 5

    jn = RequestJournal(str(tmp_path / "n"), fsync_policy="never")
    for i in range(5):
        jn.delivered(0, i)
    jn.close()
    assert jn.stats()["fsyncs"] == 1    # close() always syncs

    jn.close()                          # idempotent
    with pytest.raises(JournalWriteError):
        jn.delivered(0, 9)              # closed journal refuses appends


# ----------------------------------------------------------------------
# read side: corruption tolerance


def _write_clean(d, n_requests=6, segment_bytes=300):
    j = RequestJournal(d, fsync_policy="never", segment_bytes=segment_bytes)
    for i in range(n_requests):
        j.admitted(_fake_dr(i))
        j.delivered(i, 2)
    j.retired(0, "done", None)
    j.close()
    return j.stats()["records"]


def test_scan_truncated_tail(tmp_path):
    """A torn final record — the crash-mid-append signature — is dropped
    alone and flagged ``torn_tail``; every earlier record survives."""
    d = str(tmp_path / "j")
    total = _write_clean(d)
    segs = sorted(f for f in os.listdir(d) if f.startswith("journal-"))
    path = os.path.join(d, segs[-1])
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-9])    # mid-record, newline gone
    scan = scan_journal(d)
    assert scan.torn_tail and scan.records_dropped == 1
    assert scan.records == total - 1


def test_scan_bitflipped_checksum_mid_segment(tmp_path):
    """A flipped byte ANYWHERE fails the crc and drops that record only
    — and mid-file damage is NOT the torn-tail signature."""
    d = str(tmp_path / "j")
    total = _write_clean(d)
    segs = sorted(f for f in os.listdir(d) if f.startswith("journal-"))
    path = os.path.join(d, segs[0])     # first segment: nowhere near the tail
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x40
    open(path, "wb").write(bytes(raw))
    scan = scan_journal(d)
    assert scan.records_dropped >= 1 and not scan.torn_tail
    assert scan.records + scan.records_dropped == total


def test_scan_empty_segment_and_gap(tmp_path):
    """An empty segment contributes nothing; a deleted segment number is
    surfaced in ``segment_gaps`` and costs only its own records."""
    d = str(tmp_path / "j")
    total = _write_clean(d)
    segs = sorted(f for f in os.listdir(d) if f.startswith("journal-"))
    assert len(segs) >= 3
    open(os.path.join(d, _segment_name(99)), "wb").close()  # empty segment
    victim = os.path.join(d, segs[1])
    lost = open(victim, "rb").read().count(b"\n")
    os.remove(victim)                                       # segment gap
    scan = scan_journal(d)
    assert segs[1] in scan.segment_gaps
    assert scan.records == total - lost
    assert not scan.torn_tail           # trailing empty segment isn't torn
    assert scan_journal(str(tmp_path / "nowhere")).records == 0


def test_scan_corruption_fuzz_seeded(tmp_path):
    """Seeded fuzz: random byte flips / truncations across the segment
    set never crash the scan, and every line is either parsed or counted
    dropped — the accounting always closes."""
    rng = random.Random(1234)
    for trial in range(8):
        d = str(tmp_path / f"j{trial}")
        total = _write_clean(d, n_requests=8, segment_bytes=250)
        scannable = 0
        for name in sorted(os.listdir(d)):
            path = os.path.join(d, name)
            raw = bytearray(open(path, "rb").read())
            op = rng.random()
            if raw and op < 0.4:               # flip a byte (may merge/
                raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
            elif raw and op < 0.7:             # split lines) / truncate
                del raw[rng.randrange(len(raw)):]
            open(path, "wb").write(bytes(raw))
            lines = bytes(raw).split(b"\n")    # scan's own line model
            if lines and lines[-1] == b"":
                lines.pop()
            scannable += len(lines)
        scan = scan_journal(d)                 # must not raise
        # the accounting closes: every scannable line is parsed or
        # counted dropped, and damage can only ever LOSE records
        assert scan.records + scan.records_dropped == scannable
        assert scan.records <= total
        # whatever survived is structurally sound: replay-able metas only
        for state in scan.incomplete():
            assert isinstance(state["meta"]["prompt"], list)
            assert isinstance(state["meta"]["max_new"], int)


def test_orphan_delivered_without_admitted(tmp_path):
    """delivered/retired whose admitted record was lost are counted
    orphans, never replayed (there is nothing to replay)."""
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync_policy="never")
    j.delivered(7, 3)
    j.retired(7, "done", None)
    j.close()
    scan = scan_journal(d)
    assert scan.orphan_records == 2 and scan.requests == {}
    assert scan.incomplete() == []


# ----------------------------------------------------------------------
# chaos: the journal-write site


def test_chaos_torn_write_drops_exactly_that_record(tmp_path):
    """``journal-write`` torn: a prefix lands with no newline, the
    segment is closed, survivor appends land cleanly after it — the scan
    loses exactly the torn record."""
    d = str(tmp_path / "j")
    chaos = FaultInjector(FaultPlan(seed=0, faults=(
        FaultSpec(site="journal-write", kind="torn", at=(2,)),)))
    j = RequestJournal(d, fsync_policy="never", chaos=chaos)
    j.admitted(_fake_dr(0))            # event 0
    j.delivered(0, 1)                  # event 1
    j.delivered(0, 2)                  # event 2: TORN
    j.delivered(0, 3)                  # survivor append, fresh segment
    j.retired(0, "done", None)
    j.close()
    assert j.stats()["chaos_torn"] == 1
    scan = scan_journal(d)
    assert scan.records_dropped == 1
    assert scan.requests[0]["delivered"] == 3   # later high-water survived
    assert scan.requests[0]["retired"] == "done"


def test_chaos_corrupt_write_caught_by_checksum(tmp_path):
    """``journal-write`` corrupt: full-length line, one flipped payload
    byte — the crc catches it and the scan drops exactly it."""
    d = str(tmp_path / "j")
    chaos = FaultInjector(FaultPlan(seed=0, faults=(
        FaultSpec(site="journal-write", kind="corrupt", at=(1,)),)))
    j = RequestJournal(d, fsync_policy="never", chaos=chaos)
    j.admitted(_fake_dr(0))
    j.delivered(0, 1)                  # CORRUPT
    j.delivered(0, 2)
    j.close()
    assert j.stats()["chaos_corrupt"] == 1
    scan = scan_journal(d)
    assert scan.records_dropped == 1 and not scan.torn_tail
    assert scan.requests[0]["delivered"] == 2


def test_chaos_io_fault_fails_the_submit(tmp_path, model_and_params):
    """An ``io``-kind journal fault at admission propagates out of
    ``submit()``: the caller is never acknowledged, nothing is counted
    submitted, and the tier keeps serving afterwards."""
    model, params = model_and_params
    d = str(tmp_path / "j")
    chaos = FaultInjector(FaultPlan(seed=0, faults=(
        FaultSpec(site="journal-write", kind="io", at=(0,)),)))
    j = RequestJournal(d, fsync_policy="never", chaos=chaos)
    router = Router(_factory(model, params), 1)
    daemon = ServingDaemon(router, journal=j)    # never started: queue only
    with pytest.raises(JournalWriteError):
        daemon.submit([1, 2, 3], 4)
    cons = daemon.conservation()
    assert cons["submitted"] == 0
    assert daemon.counters["journal_errors"] == 1
    dr = daemon.submit([1, 2, 3], 4)             # next submit lands
    assert daemon.conservation()["submitted"] == 1
    daemon.close()
    scan = scan_journal(d)
    assert scan.requests[dr.id]["retired"] == "cancelled"


# ----------------------------------------------------------------------
# daemon wiring + whole-process recovery


def test_daemon_journal_clean_run_leaves_no_incomplete(tmp_path,
                                                       model_and_params):
    """A journaled wave that completes and closes cleanly leaves zero
    incomplete entries, and every delivered high-water equals the
    request's final token count."""
    model, params = model_and_params
    want = _reference(model, params)
    d = str(tmp_path / "j")
    j = RequestJournal(d, fsync_policy="interval")
    router = Router(_factory(model, params), 2)
    daemon = ServingDaemon(router, journal=j)
    with daemon:
        drs = [daemon.submit(p, 6) for p in PROMPTS]
        assert all(dr.wait(WAIT_S) for dr in drs)
        assert [dr.tokens for dr in drs] == want
        summ = daemon.summary()
        assert summ["journal"]["by_type"]["admitted"] == len(PROMPTS)
    scan = scan_journal(d)
    assert scan.incomplete() == []
    for dr in drs:
        state = scan.requests[dr.id]
        assert state["delivered"] == len(dr.tokens)
        assert state["retired"] == "done"


def test_recover_replays_everything_from_scratch(tmp_path,
                                                 model_and_params):
    """SIGKILL-before-any-work: admitted records only.  ``recover()``
    re-submits every request into a fresh tier and the replayed streams
    are token-identical to the uncrashed reference (greedy AND seeded)."""
    model, params = model_and_params
    sp = SamplingParams(temperature=0.8, top_k=8, seed=7)
    want_greedy = _reference(model, params)
    want_seeded = _reference(model, params, sampling=sp)

    d = str(tmp_path / "j")
    j = RequestJournal(d)
    router = Router(_factory(model, params), 1)
    crashed = ServingDaemon(router, journal=j)   # never started
    for p in PROMPTS:
        crashed.submit(p, 6, idempotency_key=f"key-{len(p)}")
    for p in PROMPTS:
        crashed.submit(p, 6, sampling=sp)
    # the "crash": no drain, no close — the process is simply gone
    j.sync()

    rec = recover(d, lambda: ServingDaemon(
        Router(_factory(model, params), 2),
        journal=RequestJournal(d)))
    try:
        assert rec.scan.report()["incomplete"] == 2 * len(PROMPTS)
        assert len(rec.requests) == 2 * len(PROMPTS)
        assert rec.wait(WAIT_S)
        got = [r.dr.tokens for r in rec.requests]
        assert got[:len(PROMPTS)] == want_greedy
        assert got[len(PROMPTS):] == want_seeded
        assert all(r.dr.status == "done" for r in rec.requests)
        # the client's retry keys re-bound to the replayed executions
        assert set(rec.bindings) == {f"key-{len(p)}" for p in PROMPTS}
        assert rec.report()["replayed"] == 2 * len(PROMPTS)
    finally:
        rec.daemon.close()
    # recovery composes: fresh ids never collide with crashed ids, the
    # crashed entries are closed as "replayed", the replays retired —
    # a second recovery over this directory would find nothing to do
    scan = scan_journal(d)
    crashed_ids = {r.orig_id for r in rec.requests}
    replay_ids = {r.dr.id for r in rec.requests}
    assert crashed_ids.isdisjoint(replay_ids)
    assert all(scan.requests[i]["retired"] == "replayed"
               for i in crashed_ids)
    assert all(scan.requests[i]["retired"] == "done" for i in replay_ids)
    assert scan.report()["incomplete"] == 0


def test_recover_resumes_past_delivered_high_water(tmp_path,
                                                   model_and_params):
    """The exactly-once core: a delivered high-water of k makes the
    replay re-emit ONLY tokens [k:], and the stitched transcript
    (delivered prefix + replayed suffix) is digest-identical to the
    uncrashed stream — no gaps, no duplicates."""
    model, params = model_and_params
    sp = SamplingParams(temperature=0.9, top_p=0.9, seed=21)
    want = _reference(model, params, prompts=[PROMPTS[0]], max_new=6,
                      sampling=sp)[0]
    assert len(want) == 6

    d = str(tmp_path / "j")
    j = RequestJournal(d)
    dr0 = _fake_dr(0, prompt=PROMPTS[0], max_new=6, sampling=sp,
                   idempotency_key="resume-me")
    j.admitted(dr0)
    j.delivered(0, 2)      # client held tokens [0, 2) at the crash
    j.delivered(0, 4)      # ...then [0, 4): high-water is the MAX
    j.close()

    rec = recover(d, lambda: ServingDaemon(
        Router(_factory(model, params), 1),
        journal=RequestJournal(d)))
    try:
        assert rec.wait(WAIT_S)
        (r,) = rec.requests
        assert r.orig_id == 0 and r.resume_from == 4
        assert r.dr.resume_from == 4
        # ONLY the suffix was re-emitted...
        assert r.dr.tokens == want[4:]
        assert r.dr.total_tokens == len(want)
        # ...and prefix + suffix stitch into the exact uncrashed stream
        stitched = want[:4] + list(r.dr.tokens)
        assert transcript_digest(stitched) == transcript_digest(want)
        assert rec.bindings["resume-me"] is r.dr
    finally:
        rec.daemon.close()


def test_recover_lapsed_deadline_retires_cancelled(tmp_path,
                                                   model_and_params):
    """A request whose deadline lapsed while the process was dead is
    re-admitted already overdue and retires ``cancelled`` through the
    normal path — counted and journaled, never silently dropped."""
    model, params = model_and_params
    d = str(tmp_path / "j")
    j = RequestJournal(d)
    dr0 = _fake_dr(0, prompt=[1, 2, 3], max_new=6, deadline_s=0.5)
    meta_patch = dict(wall_t=1.0)      # admitted "long ago" in wall time
    # re-encode the admitted record with an ancient wall_t
    j.append({
        "t": "admitted", "id": 0, "prompt": [1, 2, 3], "max_new": 6,
        "deadline_s": 0.5, "priority": 0, "ttft_slo_s": None,
        "tpot_slo_s": None, "sampling": None, "key": None,
        "fp": dr0.fingerprint, "resume_from": 0, **meta_patch,
    })
    j.close()

    rec = recover(d, lambda: ServingDaemon(
        Router(_factory(model, params), 1),
        journal=RequestJournal(d)))
    try:
        assert rec.wait(WAIT_S)
        (r,) = rec.requests
        assert r.dr.status == "cancelled"
    finally:
        rec.daemon.close()
    cons = rec.daemon.conservation()
    assert cons["conserved"] and cons["cancelled"] >= 1
    # journal closure: the replay got its terminal record
    scan = scan_journal(d)
    assert scan.requests[r.dr.id]["retired"] == "cancelled"


def test_encode_decode_property(tmp_path):
    """Every encoded line is 8 hex chars + space + compact JSON +
    newline, and decodes back to the record."""
    from distributed_tensorflow_ibm_mnist_tpu.serving.journal import _decode
    rec = {"t": "delivered", "id": 3, "n": 11}
    line = _encode(rec)
    assert line.endswith(b"\n") and line[8:9] == b" "
    assert _decode(line[:-1]) == rec
    assert _decode(b"") is None
    assert _decode(b"deadbeef {not json}") is None
    flipped = bytearray(line[:-1])
    flipped[12] ^= 0x02
    assert _decode(bytes(flipped)) is None


# ----------------------------------------------------------------------
# bench smoke: the crash bench's quick mode end to end


@pytest.mark.slow
def test_bench_crash_quick_gates():
    import json
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu", DTM_BENCH_QUICK="1")
    out = subprocess.run(
        [sys.executable, str(repo / "scripts" / "bench_crash.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, (
        f"bench_crash quick failed rc={out.returncode}; "
        f"stderr tail: {out.stderr[-800:]!r}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "crash"
    assert rec["passed"] is True
    assert all(rec["gates"].values()), rec["gates"]

"""Data parallelism over an 8-device virtual CPU mesh (SURVEY.md §4).

Validates the NCCL-replacement semantics: a shard_map DP step with gradient
pmean over the ``data`` axis is numerically the single-device full-batch step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
from distributed_tensorflow_ibm_mnist_tpu.data import synthetic_mnist
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.data_parallel import (
    make_dp_epoch_runner,
    make_dp_train_step,
    replicate,
    shard_dataset,
)


def _setup(n=512, dtype=jnp.float32):
    data = synthetic_mnist(n_train=n, n_test=64, seed=0)
    model = get_model("mlp", num_classes=10, hidden=(64,), dtype=dtype)
    tx = optax.sgd(0.1)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    return data, model, tx, state


def test_mesh_axes(eight_devices):
    mesh = make_mesh(dp=8)
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1
    mesh2 = make_mesh(dp=4, tp=2)
    assert mesh2.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}


def test_dp_step_matches_single_device(eight_devices):
    """pmean-of-shard-grads == full-batch grad: same params after one step."""
    data, model, tx, state = _setup()
    batch = {
        "image": jnp.asarray(data["train_images"][:64]),
        "label": jnp.asarray(data["train_labels"][:64]),
    }

    single_step = jax.jit(make_train_step(model, tx))
    single_out, _ = single_step(state, batch)

    mesh = make_mesh(dp=8)
    dp_step = make_dp_train_step(model, tx, mesh)
    dp_state = replicate(mesh, state)
    dp_out, metrics = dp_step(dp_state, batch)

    for a, b in zip(jax.tree.leaves(single_out.params), jax.tree.leaves(dp_out.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)
    assert np.isfinite(float(metrics["loss"]))


def test_dp_epoch_runner_learns(eight_devices):
    data, model, tx, state = _setup(n=1024)
    mesh = make_mesh(dp=8)
    imgs, labs = shard_dataset(mesh, data["train_images"], data["train_labels"])
    state = replicate(mesh, state)
    run_epoch = make_dp_epoch_runner(model, tx, global_batch=128, mesh=mesh)
    for epoch in range(6):
        state, metrics = run_epoch(state, imgs, labs, jax.random.PRNGKey(epoch))
    assert float(jnp.mean(metrics["accuracy"])) > 0.6
    # 1024 samples / 128 global batch = 8 steps per epoch
    assert int(state.step) == 6 * 8


def test_shard_dataset_layout(eight_devices):
    data, *_ = _setup(n=80)
    mesh = make_mesh(dp=8)
    imgs, labs = shard_dataset(mesh, data["train_images"], data["train_labels"])
    assert imgs.shape[0] == 80  # divisible, nothing dropped
    assert len(imgs.sharding.device_set) == 8


def test_parallel_eval_sharded_and_matching(eight_devices):
    """Eval runs under the run's own mesh: the test set is sharded over
    'data' (padded, never dropped) and metrics equal the single-device eval
    exactly (VERDICT.md round-1 item 3)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=100,
        batch_size=32, epochs=1, quiet=True, seed=7, eval_batch_size=48,
    )
    t8 = Trainer(RunConfig(name="dp8", dp=8, **base))
    t1 = Trainer(RunConfig(name="dp1", dp=1, **base))

    # the eval batch really is sharded over 'data'
    assert t8.test_images.sharding.spec == P("data", None, None, None)
    assert t8.test_images.shape[0] == 104  # 100 padded up to a multiple of 8

    e8, e1 = t8.evaluate(), t1.evaluate()  # same seed => identical init params
    assert abs(e8["accuracy"] - e1["accuracy"]) < 1e-6
    assert abs(e8["loss"] - e1["loss"]) < 1e-5

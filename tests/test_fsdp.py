"""FSDP (ZeRO-3 over 'data') on the 8-device virtual CPU mesh.

Acceptance mirrors the TP test: the fully-sharded run of the UNCHANGED train
step is numerically the single-device run, params AND adam moments really
live sharded over ``data``, and the TP+FSDP composition places every large
leaf on some axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.fsdp import (
    fsdp_rule,
    make_fsdp_specs,
    make_fsdp_train_step,
    shard_train_state,
)
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    make_param_specs,
    megatron_dense_rule,
)


def _mlp_state(hidden=(64, 64)):
    model = get_model("mlp", num_classes=10, hidden=hidden, dtype=jnp.float32)
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    return model, tx, state


def _batches(n_steps=3, batch=64, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        out.append({
            "image": jnp.asarray(rng.integers(0, 255, size=(batch, 28, 28, 1), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(batch,)).astype(np.int32)),
        })
    return out


def test_fsdp_rule_shards_largest_divisible_dim():
    rule = fsdp_rule(n_shards=8, min_size=64)
    w = jnp.zeros((784, 64))
    assert rule(("dense_0", "kernel"), w) == P("data", None)
    # largest dim not divisible by 8 -> falls to the divisible one
    w2 = jnp.zeros((17, 64))
    assert rule(("x", "kernel"), w2) == P(None, "data")
    # nothing divisible -> replicated
    assert rule(("x", "kernel"), jnp.zeros((17, 33))) == P()
    # small leaves stay replicated
    assert rule(("dense_0", "bias"), jnp.zeros((10,))) == P()
    # scalars stay replicated
    assert rule(("count",), jnp.zeros(())) == P()


def test_fsdp_composes_with_tp_rule():
    rule = fsdp_rule(n_shards=2, min_size=64, base_rule=megatron_dense_rule())
    # TP keeps its dim, FSDP shards the remaining free dim over 'data'
    assert rule(("dense_0", "kernel"), jnp.zeros((784, 64))) == P("data", "model")
    assert rule(("dense_1", "kernel"), jnp.zeros((64, 784))) == P("model", "data")
    # leaves TP ignores get plain FSDP over 'data'
    assert rule(("logits", "kernel"), jnp.zeros((64, 10))) == P("data", None)
    # a free dim that doesn't divide stays unsharded, TP dim kept
    assert rule(("dense_0", "kernel"), jnp.zeros((17, 64))) == P(None, "model")


def test_fsdp_matches_single_device(eight_devices):
    mesh = make_mesh(dp=8)
    model, tx, state = _mlp_state(hidden=(64, 64))
    specs = make_fsdp_specs(state.params, mesh, min_size=64)
    batches = _batches()

    ref_step = jax.jit(make_train_step(model, tx))
    ref_state = state
    for b in batches:
        ref_state, ref_metrics = ref_step(ref_state, b)

    fs_state = shard_train_state(mesh, state, specs)
    fs_step = make_fsdp_train_step(model, tx, mesh, specs, state)
    for b in batches:
        fs_state, fs_metrics = fs_step(fs_state, b)

    # params and adam moments really sharded over 'data'
    k0 = fs_state.params["dense_0"]["kernel"]
    assert k0.sharding.spec == P("data", None)
    mu0 = fs_state.opt_state[0].mu["dense_0"]["kernel"]
    assert mu0.sharding.spec == k0.sharding.spec
    # each device holds 1/8 of the leaf
    shard_elems = {s.data.size for s in k0.addressable_shards}
    assert shard_elems == {k0.size // 8}

    np.testing.assert_allclose(
        float(fs_metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(fs_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert int(fs_state.step) == len(batches)


def test_fsdp_tp_2d_layout_runs(eight_devices):
    """TP within (model=2), FSDP across (data=4): the standard 2D layout."""
    mesh = make_mesh(dp=4, tp=2)
    model, tx, state = _mlp_state(hidden=(64, 64))
    specs = make_param_specs(
        state.params,
        fsdp_rule(n_shards=4, min_size=64, base_rule=megatron_dense_rule()),
    )
    st = shard_train_state(mesh, state, specs)
    step = make_fsdp_train_step(model, tx, mesh, specs, state)
    for b in _batches(n_steps=2):
        st, metrics = step(st, b)
    assert np.isfinite(float(metrics["loss"]))
    # 2D layout: TP over 'model' AND ZeRO over 'data' on the same kernel
    assert st.params["dense_0"]["kernel"].sharding.spec == P("data", "model")
    assert st.params["logits"]["kernel"].sharding.spec == P("data", None)


def test_trainer_config_driven_fsdp(eight_devices):
    """RunConfig(fsdp=True, dp=8): ZeRO-3 via config alone — params AND adam
    moments sharded over 'data', trajectory matches single-device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="mlp", model_kwargs={"hidden": (256, 256), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=1, lr=2e-3, quiet=True, seed=3, eval_batch_size=128,
    )
    t_f = Trainer(RunConfig(name="fsdp", dp=8, fsdp=True, **base))
    t_f.fit()
    k = t_f.state.params["dense_0"]["kernel"]
    mu = t_f.state.opt_state[0].mu["dense_0"]["kernel"]
    assert k.sharding.spec == P("data", None)
    assert mu.sharding.spec == P("data", None)  # the ZeRO memory win

    t_1 = Trainer(RunConfig(name="one", dp=1, **base))
    t_1.fit()
    for a, b in zip(jax.tree.leaves(jax.device_get(t_f.state.params)),
                    jax.tree.leaves(jax.device_get(t_1.state.params))):
        # 1e-2: a full epoch of adam steps amplifies f32 reduction-order
        # differences (GSPMD reduce-scatter vs single-device sum) on
        # sign-borderline elements — measured 4.8e-3 max on 8/65536 elems
        # (CPU backend, jax 0.4.37).  STEP-level parity is pinned tight by
        # test_fsdp_matches_single_device (atol 1e-5); this bound only
        # claims the epoch trajectories stay equivalent at update scale.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-2)


def test_trainer_fsdp_batchnorm_model(eight_devices):
    """fsdp + a BatchNorm model must not inject a named-axis pmean into the
    GSPMD path (regression: NameError 'unbound axis name: data')."""
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        model="resnet20", synthetic=True, n_train=128, n_test=32,
        batch_size=64, epochs=1, dp=8, fsdp=True, quiet=True, eval_batch_size=32,
    )
    t = Trainer(cfg)
    assert getattr(t.model, "axis_name", None) is None
    s = t.fit()
    assert s["epochs_run"] == 1


def test_trainer_fsdp_requires_dp(eight_devices):
    import jax.numpy as jnp
    import pytest

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="fsdp.*dp"):
        Trainer(RunConfig(model="mlp", synthetic=True, n_train=256, n_test=64,
                          batch_size=32, dp=1, fsdp=True, quiet=True))

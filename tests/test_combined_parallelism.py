"""DP x TP x SP composed in ONE jitted train step (2x2x2 over 8 devices).

The flagship composition: ViT with Megatron-sharded MLPs (GSPMD over
``model``), ring attention (shard_map island over ``seq``), batch over
``data`` — numerically the single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import make_ring_attention
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    make_param_specs,
    make_tp_train_step,
    megatron_dense_rule,
    shard_train_state,
)


def test_dp_tp_sp_combined_matches_single_device(eight_devices):
    mesh = make_mesh(dp=2, tp=2, sp=2)
    kw = dict(patch_size=7, dim=32, depth=2, heads=2, num_classes=10, dtype=jnp.float32)
    vit_plain = get_model("vit", **kw)
    vit_sharded = get_model("vit", attn_fn=make_ring_attention(mesh), **kw)

    # SGD: linear in the gradient, so f32 reduction-order noise stays 1e-6ish
    # (adam's g/sqrt(nu) amplifies near-zero grads to ~lr regardless of size)
    tx = optax.sgd(0.1)
    sample = jnp.zeros((1, 28, 28, 1), jnp.uint8)
    state = TrainState.create(vit_plain, tx, jax.random.PRNGKey(0), sample)
    specs = make_param_specs(state.params, megatron_dense_rule())

    rng = np.random.default_rng(0)
    batches = [
        {
            "image": jnp.asarray(rng.integers(0, 255, size=(8, 28, 28, 1), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(8,)).astype(np.int32)),
        }
        for _ in range(2)
    ]

    ref_state = state
    ref_step = jax.jit(make_train_step(vit_plain, tx))
    for b in batches:
        ref_state, ref_m = ref_step(ref_state, b)

    sh_state = shard_train_state(mesh, state, specs)
    sh_step = make_tp_train_step(vit_sharded, tx, mesh, specs, state)
    for b in batches:
        sh_state, sh_m = sh_step(sh_state, b)

    # MLP params really sharded over 'model'
    from jax.sharding import PartitionSpec as P

    k = sh_state.params["block_0"]["dense_0"]["kernel"]
    assert k.sharding.spec == P(None, "model")

    np.testing.assert_allclose(float(sh_m["loss"]), float(ref_m["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_trainer_config_driven_dp_tp_sp(eight_devices):
    """RunConfig(dp=2, tp=2, sp=2) trains a ViT end to end: Megatron GSPMD
    specs + ring-attention islands, one compiled epoch scan, eval included —
    the whole composition driven by config fields alone (no library code in
    user hands)."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="dp_tp_sp", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 2, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=2, lr=1e-3, dp=2, tp=2, sp=2, quiet=True,
        eval_batch_size=128,
    )
    t = Trainer(cfg)
    assert t.mesh.shape == {"data": 2, "model": 2, "seq": 2, "pipe": 1}
    s = t.fit()
    assert s["epochs_run"] == 2
    assert 0.0 <= s["best_test_accuracy"] <= 1.0
    # params really live on the 2x2x2 mesh (sharded or replicated, all committed)
    leaf = jax.tree.leaves(t.state.params)[0]
    assert len(leaf.sharding.mesh.devices.flatten()) == 8


def test_trainer_sp_requires_sequence_model(eight_devices):
    import pytest

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="attn_fn"):
        Trainer(RunConfig(model="lenet5", synthetic=True, n_train=256, n_test=64,
                          batch_size=32, sp=2, quiet=True))


def test_trainer_sp_checkpoint_resume(eight_devices, tmp_path):
    """sp>1 (tp=1) checkpoint resume must re-shard onto the mesh, not commit
    the state to one device (regression: restore only checked tp)."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="sp_ck", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 2, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=128, n_test=32,
        batch_size=32, epochs=1, lr=1e-3, dp=1, tp=1, sp=2, quiet=True,
        eval_batch_size=32, checkpoint_dir=str(tmp_path / "spck"),
    )
    t1 = Trainer(cfg)
    t1.fit()
    t2 = Trainer(cfg.replace(resume=True))
    t2.fit()  # restores, then trains another epoch on the mesh-jitted runner
    assert int(jax.device_get(t2.state.step)) == 2 * t2.steps_per_epoch

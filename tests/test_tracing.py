"""End-to-end tracing + compile accounting (ISSUE 6, utils/tracing.py).

The decisive properties:

* EXPORT VALIDITY — a chaos-enabled serving soak exports STRICT
  Chrome-trace JSON: every span closed, every parent resolving, no
  NaN/Infinity tokens (``validate_trace`` is the mechanical check, and
  the tests also pin what it checks).
* CORRELATION — each request's root span duration matches its reported
  latency (one shared monotonic clock), and injected chaos faults attach
  to the requests they actually hit.
* COMPILE ACCOUNTING — ``CompileTracker`` counts only programs actually
  compiled (repeats are cache hits: zero), attributed to the site that
  triggered them.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
    CompileTracker,
    Tracer,
    load_trace,
    validate_trace,
)

KW = dict(num_classes=16, dim=64, depth=2, heads=4, dtype=jnp.float32)


def _model_and_params(seed=0):
    model = get_model("causal_lm", **KW)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _spans(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"]


# ----------------------------------------------------------------------
# Tracer unit behaviour


def test_tracer_span_tree_counters_and_summary():
    clock = iter(np.arange(0.0, 10.0, 0.125))
    tr = Tracer(clock=lambda: float(next(clock)))
    root = tr.begin("request", cat="serving", req=7)
    child = tr.begin("queue", cat="serving", parent=root)
    tr.end(child)
    with tr.span("decode", cat="serving", parent=root, slot=1):
        tr.instant("first_token", cat="serving", parent=root, slot=1)
    tr.counter("queue_depth", 3)
    tr.end(root, status="done")
    assert tr.open_spans == 0 and tr.dropped == 0

    events = tr.events()
    assert [e["name"] for e in events] == [
        "queue", "first_token", "decode", "queue_depth", "request"]
    req = events[-1]
    assert req["args"]["req"] == 7 and req["args"]["status"] == "done"
    # children closed before the root carry its id as parent
    assert events[0]["parent"] == req["id"]

    s = tr.summary()
    assert s["events"] == len(events) and s["open_spans"] == 0
    assert s["phases"]["serving/request"]["n"] == 1
    assert s["phases"]["serving/decode"]["total_s"] > 0
    assert s["counters"]["queue_depth"] == 3.0
    json.dumps(s, allow_nan=False)  # strict-JSON clean


def test_tracer_end_of_unknown_span_is_ignored():
    tr = Tracer()
    tr.end(12345)  # never began: must not raise (retirement races)
    sid = tr.begin("x")
    tr.end(sid)
    tr.end(sid)  # double end: second is a no-op
    assert tr.open_spans == 0 and len(tr.events()) == 1


def test_tracer_ring_bound_drops_closed_never_open():
    tr = Tracer(capacity=8)
    root = tr.begin("request")  # open: must survive any overflow
    for i in range(50):
        tr.instant("tick", i=i)
    assert len(tr.events()) == 8 and tr.dropped == 42
    tr.end(root, status="done")  # still closable after the wrap
    assert tr.open_spans == 0
    assert tr.summary()["dropped"] == 43  # the close evicted one more tick
    # the root landed even though the instants around it were evicted
    assert tr.events()[-1]["name"] == "request"


def test_export_strict_json_validates_and_names_tracks(tmp_path):
    tr = Tracer()
    tid = tr.track("req 0")
    root = tr.begin("request", cat="serving", tid=tid, req=0)
    with tr.span("decode", cat="serving", parent=root, tid=tid):
        pass
    tr.end(root, status="done")
    tr.counter("queue_depth", 0)
    path = tmp_path / "t.trace.json"
    out = tr.export_trace(str(path))
    assert out["events"] > 0 and out["path"] == str(path)

    assert validate_trace(str(path)) == []
    doc = load_trace(str(path))
    assert doc["displayTimeUnit"] == "ms"
    names = {(e["ph"], e.get("name")) for e in doc["traceEvents"]}
    assert ("M", "thread_name") in names and ("C", "queue_depth") in names
    spans = _spans(doc)
    ids = [e["args"]["id"] for e in spans]
    assert len(ids) == len(set(ids)) == 2
    # the child's parent resolves to the root's exported id
    by_name = {e["name"]: e for e in spans}
    assert by_name["decode"]["args"]["parent"] == by_name["request"]["args"]["id"]
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in spans)


def test_export_flags_open_spans_and_validator_rejects(tmp_path):
    tr = Tracer()
    tr.begin("request", req=1)  # never ended
    path = tmp_path / "open.trace.json"
    tr.export_trace(str(path))
    doc = load_trace(str(path))
    assert any(e["ph"] == "B" for e in doc["traceEvents"])
    problems = validate_trace(str(path))
    assert problems and any("unclosed" in p for p in problems)


def test_export_drops_dangling_parent_refs(tmp_path):
    """A child whose parent was ring-evicted exports WITHOUT the parent
    arg — a wrapped trace still passes parent-resolution validation."""
    tr = Tracer(capacity=2)
    root = tr.begin("request")
    tr.end(root)
    for i in range(5):  # evict the root from the ring
        tr.instant("tick", i=i)
    child = tr.begin("late", parent=root)
    tr.end(child)
    path = tmp_path / "wrap.trace.json"
    tr.export_trace(str(path))
    assert validate_trace(str(path)) == []
    late = [e for e in _spans(load_trace(str(path))) if e["name"] == "late"]
    assert late and "parent" not in late[0]["args"]


def test_load_trace_rejects_nonstrict_json(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"traceEvents": [{"ph": "X", "ts": NaN}]}')
    with pytest.raises(ValueError, match="non-strict"):
        load_trace(str(p))
    assert any("strict" in s or "parse" in s for s in validate_trace(str(p)))


# ----------------------------------------------------------------------
# CompileTracker


def test_compile_tracker_singleton_and_site_attribution():
    tracker = CompileTracker.install()
    assert CompileTracker.install() is tracker  # one per process
    if tracker.mode == "unavailable":
        pytest.skip("no compile hook on this jax build")

    before = tracker.snapshot()
    f = jax.jit(lambda x: x * 2 + 1)
    with tracker.site("test_site_a"):
        f(jnp.arange(7.0)).block_until_ready()
    mid = tracker.snapshot()
    d1 = CompileTracker.delta(mid, before)
    assert d1["n_compiled_programs"] >= 1
    assert "test_site_a" in d1["by_site"]

    # the SAME program again: a tracing-cache hit compiles nothing
    with tracker.site("test_site_b"):
        f(jnp.arange(7.0)).block_until_ready()
    d2 = CompileTracker.delta(tracker.snapshot(), mid)
    assert d2["n_compiled_programs"] == 0 and d2["by_site"] == {}


def test_compile_tracker_bound_tracer_gets_instants():
    tracker = CompileTracker.install()
    if tracker.mode != "monitoring":
        pytest.skip("xla_compile instants need the monitoring hook")
    tr = Tracer()
    tracker.bind(tr)
    try:
        with tracker.site("bound_site"):
            jax.jit(lambda x: x - 3)(jnp.arange(5.0)).block_until_ready()
    finally:
        tracker.bind(None)
    hits = [e for e in tr.events()
            if e["name"] == "xla_compile" and e["args"]["site"] == "bound_site"]
    assert hits and hits[0]["args"]["compile_time_s"] > 0


# ----------------------------------------------------------------------
# serving integration: the ISSUE 6 acceptance pin


def _traced_engine(model, params, tracer, chaos=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 24)
    return InferenceEngine(
        model, params, chaos=chaos, tracer=tracer,
        scheduler=FIFOScheduler(max_len=kw["max_len"], buckets=(8,)), **kw)


def test_serving_trace_end_to_end_with_chaos(tmp_path):
    """Chaos-enabled serving run -> export -> validate: every span
    closed, parents resolve, strict JSON; each request's root span
    duration matches its reported latency (shared clock); the injected
    fault attaches to the request it hit and no other."""
    model, params = _model_and_params()
    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-admit", kind="poison", at=(1,)),
    )))
    tr = Tracer()
    eng = _traced_engine(model, params, tr, chaos=inj, decode_ahead=2,
                         prefix_cache_bytes=16 << 20)
    rng = np.random.default_rng(0)
    reqs = []
    repeat = np.asarray([3, 1, 4, 1, 5], np.int32)  # prefix-cache bait
    for i in range(6):
        prompt = (repeat if i >= 4 else
                  rng.integers(1, 16, size=(2 + i % 4,)).astype(np.int32))
        reqs.append(eng.submit(prompt, max_new=3 + i % 3))
    done = eng.run()
    assert len(done) == 6 and tr.open_spans == 0

    path = tmp_path / "serving.trace.json"
    tr.export_trace(str(path))
    assert validate_trace(str(path)) == []
    doc = load_trace(str(path))
    spans = _spans(doc)
    roots = {e["args"]["req"]: e for e in spans if e["name"] == "request"}
    assert set(roots) == {r.id for r in reqs}

    for r in reqs:
        root = roots[r.id]
        if r.status == "done":
            want_s = r.finish_t - r.submit_t
            assert abs(root["dur"] / 1e6 - want_s) < 0.05, r.id
            # child phases tile the root: queue+admit+decode <= total
            kids = [e for e in spans
                    if e["args"].get("parent") == root["args"]["id"]]
            assert {"queue", "decode"} <= {k["name"] for k in kids}
            assert sum(k["dur"] for k in kids if k["name"] != "prefill"
                       ) <= root["dur"] * 1.02 + 1000
        assert root["args"]["status"] == r.status

    # the fault landed on request 1's track, parented under ITS root
    faults = [e for e in doc["traceEvents"] if e["name"] == "chaos_fault"]
    assert len(faults) == 1
    assert faults[0]["args"]["parent"] == roots[reqs[1].id]["args"]["id"]
    assert faults[0]["args"]["site"] == "serving-admit"
    assert reqs[1].status == "failed"
    assert roots[reqs[1].id]["args"]["status"] == "failed"

    # prefix-cache hit instants attach to the repeated-prompt requests
    hits = [e for e in doc["traceEvents"] if e["name"] == "prefix_cache_hit"]
    assert len(hits) == 1  # req 5 hits what req 4 stored
    assert hits[0]["args"]["parent"] == roots[reqs[5].id]["args"]["id"]

    # stats carry the compile ledger (null only when the hook is absent)
    s = eng.stats.summary()
    if CompileTracker.install().mode != "unavailable":
        assert s["n_compiled_programs"] >= 1
        assert any(k.startswith("prefill[b8]") for k in s["compile_by_site"])
    else:
        assert s["n_compiled_programs"] is None


def test_engine_close_closes_all_request_spans():
    model, params = _model_and_params()
    tr = Tracer()
    eng = _traced_engine(model, params, tr)
    for i in range(4):  # 2 slots: 2 admit, 2 stay queued
        eng.submit(np.asarray([1, 2, 3], np.int32), max_new=4)
    eng.close()
    assert tr.open_spans == 0
    statuses = [e["args"]["status"] for e in tr.events()
                if e["name"] == "request"]
    assert len(statuses) == 4 and set(statuses) == {"cancelled"}


def test_engine_rejects_two_different_tracers():
    model, params = _model_and_params()
    sched = FIFOScheduler(max_len=24, buckets=(8,), tracer=Tracer())
    with pytest.raises(ValueError, match="tracer"):
        InferenceEngine(model, params, slots=2, max_len=24,
                        tracer=Tracer(), scheduler=sched)
    # engine adopts the scheduler's tracer when it has none
    eng = InferenceEngine(model, params, slots=2, max_len=24, scheduler=sched)
    assert eng._tracer is sched.tracer


def test_tracerless_engine_has_no_tracer_state():
    """The nil-guard zero-overhead contract, structurally: no tracer ->
    every site is one attribute test, and no spans exist anywhere."""
    model, params = _model_and_params()
    eng = InferenceEngine(
        model, params, slots=2, max_len=24,
        scheduler=FIFOScheduler(max_len=24, buckets=(8,)))
    assert eng._tracer is None and eng.scheduler.tracer is None
    r = eng.submit(np.asarray([1, 2], np.int32), max_new=3)
    eng.run()
    assert r.trace is None and r.status == "done"


# ----------------------------------------------------------------------
# training integration


def test_trainer_trace_spans_and_compile_summary(tmp_path):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    tr = Tracer()
    cfg = RunConfig(
        model="mlp", model_kwargs={"hidden": (32,)}, synthetic=True,
        n_train=256, n_test=64, batch_size=64, epochs=2, dp=1, quiet=True,
        eval_every=1, checkpoint_every=1, input_mode="stream",
        stream_chunk=2, checkpoint_dir=str(tmp_path / "ck"),
    )
    t = Trainer(cfg, tracer=tr)
    summary = t.fit()
    assert tr.open_spans == 0
    names = {(e["cat"], e["name"]) for e in tr.events()}
    assert {("train", "epoch_dispatch"), ("train", "fetch"),
            ("train", "eval"), ("train", "h2d"), ("train", "dispatch"),
            ("train", "checkpoint_save")} <= names

    # restore traces too
    t2 = Trainer(cfg.replace(resume=True), tracer=tr)
    step = t2.restore_checkpoint()
    assert step > 0
    restores = [e for e in tr.events() if e["name"] == "checkpoint_restore"]
    assert restores and restores[-1]["args"]["restored_step"] == step

    path = tmp_path / "train.trace.json"
    tr.export_trace(str(path))
    assert validate_trace(str(path)) == []

    # fit summary carries the compile ledger
    if CompileTracker.install().mode != "unavailable":
        assert summary["n_compiled_programs"] >= 1
        assert summary["compile_time_s"] >= 0
    else:
        assert summary["n_compiled_programs"] is None


def test_elastic_restart_instant_lands_on_timeline(tmp_path):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector as FI,
        FaultPlan as FP,
        FaultSpec as FS,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
    from distributed_tensorflow_ibm_mnist_tpu.utils.elastic import (
        run_with_recovery,
    )

    tr = Tracer()
    cfg = RunConfig(
        model="mlp", model_kwargs={"hidden": (32,)}, synthetic=True,
        n_train=256, n_test=64, batch_size=64, epochs=2, dp=1, quiet=True,
        checkpoint_every=1, checkpoint_dir=str(tmp_path / "ck"),
        input_mode="stream", stream_chunk=2,
    )
    inj = FI(FP(faults=(FS(site="data-batch", kind="io", at=(3,)),)))
    summary = run_with_recovery(
        lambda: Trainer(cfg, chaos=inj), max_restarts=2,
        backoff_base_s=0.0, tracer=tr)
    assert summary["restarts"] == 1
    restarts = [e for e in tr.events() if e["name"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["cat"] == "elastic"
    assert restarts[0]["args"]["exception"] == "OSError"
    assert restarts[0]["args"]["attempt"] == 1
    # the supervised trainer inherited the tracer: the fit spans of every
    # attempt land on the SAME timeline as the restart instant
    assert any(e["name"] == "epoch_dispatch" for e in tr.events())
    assert tr.open_spans == 0


# ----------------------------------------------------------------------
# trace_report


def test_trace_report_analyze_and_cli(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)

    model, params = _model_and_params()
    tr = Tracer()
    eng = _traced_engine(model, params, tr)
    for i in range(3):
        eng.submit(np.asarray([1, 2, 3 + i], np.int32), max_new=3)
    eng.run()
    path = tmp_path / "r.trace.json"
    tr.export_trace(str(path))

    rep = trace_report.analyze(load_trace(str(path)))
    assert rep["n_spans"] > 0
    assert any(p["phase"] == "serving/request" for p in rep["phases"])
    assert len(rep["requests"]) == 3
    for r in rep["requests"]:
        assert r["status"] == "done"
        assert r["total_ms"] >= sum(r["phases_ms"].values()) * 0.98 - 1.0
        assert "decode" in r["phases_ms"]

    # the CLI form: --json emits the same analysis as one strict line
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "trace_report.py"),
         str(path), "--json", "--strict"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout)
    assert rec["problems"] == [] and len(rec["requests"]) == 3


def test_trace_report_counter_track_rollup(tmp_path):
    """ISSUE 11 satellite: counter tracks roll up to n/min/mean/max/last
    over the recorded CHANGE points (the tracer dedups repeats, so the
    mean is over distinct recorded values, not time-weighted)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)

    tr = Tracer()
    for v in (3, 1, 8, 4):
        tr.counter("queue_depth", v)
    tr.counter("occupied_slots", 2)
    path = tmp_path / "c.trace.json"
    tr.export_trace(str(path))

    rep = trace_report.analyze(load_trace(str(path)))
    q = rep["counter_stats"]["queue_depth.value"]
    assert q["n"] == 4
    assert q["min"] == 1 and q["max"] == 8 and q["last"] == 4
    assert q["mean"] == 4.0
    o = rep["counter_stats"]["occupied_slots.value"]
    assert o["n"] == 1 and o["last"] == 2
    # the legacy last-value map stays for compat
    assert rep["counters_last"]["queue_depth.value"] == 4
    json.loads(json.dumps(rep, allow_nan=False))


# ----------------------------------------------------------------------
# bench harness smoke (slow: subprocess + fresh jax init); the fast legs
# above cover the library — this pins the harness itself


@pytest.mark.slow
def test_bench_compile_census_quick_smoke():
    """The compile-census acceptance figure, end to end in a subprocess:
    n_compiled_programs moves when (and only when) a new bucket appears."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys, os, json; "
        "sys.path.insert(0, os.path.join(%r, 'scripts')); "
        "from bench_serving import run_compile_census; "
        "print(json.dumps(run_compile_census(2)))" % root)
    env = dict(os.environ, JAX_PLATFORMS="cpu", DTM_BENCH_QUICK="1")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec["mode"] == "unavailable":
        pytest.skip("no compile hook in subprocess jax")
    assert rec["repeat_compiles_zero"] is True
    assert rec["new_bucket_compiles"] is True
    census = rec["legs"]
    assert census["bucket16_first"]["n_new_programs"] > 0
    assert census["bucket32_new"]["n_new_programs"] >= 1
    # the new bucket's compiles are its prefill program — decode/insert/
    # reset are bucket-invariant and must all be cache hits
    assert "prefill[b32]" in census["bucket32_new"]["by_site"]
    for site in ("decode_window", "slot_insert", "slot_reset"):
        assert not any(k.startswith(site)
                       for k in census["bucket32_new"]["by_site"])
    # ISSUE 7: the census is a regression GATE — every leg pinned to its
    # budget, and the paged family compiles once, never per request
    assert rec["census_ok"] is True, rec["over_budget"]
    assert set(rec["budget"]) == set(census)
    assert census["paged_cold"]["n_new_programs"] > 0
    assert any(k.startswith("extend[") for k in census["paged_cold"]["by_site"])
    assert census["paged_repeat"]["n_new_programs"] == 0

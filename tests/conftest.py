"""Test env: force an 8-device virtual CPU mesh BEFORE jax initializes.

This is the SURVEY.md §4 strategy: distributed tests run against
``--xla_force_host_platform_device_count=8`` on CPU, replacing the
reference's "run it on K8s to find out" with a real multi-device test in CI.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize imports jax at interpreter start, which freezes
# jax_platforms from the env before this file runs — override via config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip(f"need 8 virtual devices, have {len(devices)}")
    return devices

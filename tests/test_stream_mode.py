"""Streaming input mode: host-resident data + C++ prefetcher feeding the
per-step compiled train step — single-device and DP."""

import pytest

from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig


def _cfg(**kw):
    base = dict(
        model="mlp", model_kwargs={"hidden": (32,)}, synthetic=True,
        n_train=1024, n_test=256, batch_size=128, epochs=3, dp=1, quiet=True,
    )
    base.update(kw)
    return RunConfig(**base)


def test_stream_mode_trains():
    summary = Trainer(_cfg(input_mode="stream")).fit()
    assert summary["epochs_run"] == 3
    assert summary["best_test_accuracy"] > 0.35


def test_stream_mode_dp(eight_devices):
    # batch 256 -> only 4 steps/epoch; give it more epochs to learn
    summary = Trainer(_cfg(input_mode="stream", dp=8, batch_size=256, epochs=8)).fit()
    assert summary["epochs_run"] == 8
    assert summary["best_test_accuracy"] > 0.35


def test_stream_matches_device_mode_quality():
    """Same config either mode reaches comparable accuracy (data orders differ)."""
    dev = Trainer(_cfg(epochs=4)).fit()
    stream = Trainer(_cfg(epochs=4, input_mode="stream")).fit()
    assert abs(dev["best_test_accuracy"] - stream["best_test_accuracy"]) < 0.15


def test_bad_input_mode_rejected():
    with pytest.raises(ValueError, match="input_mode"):
        Trainer(_cfg(input_mode="nope"))

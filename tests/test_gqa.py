"""Grouped-query attention (heads_kv < heads) across the stack.

The flash kernel routes q-heads to shared K/V blocks via BlockSpec index
maps (ops/flash_attention._kv_spec) — the ground truth is the dense
reference with group-repeated K/V (parallel/ring_attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention import flash_attention
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import vanilla_attention


def _qkv(b=2, s=32, h=4, hkv=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hkv", [1, 2])  # MQA and 2-group GQA
def test_flash_gqa_forward_matches_dense(causal, hkv):
    q, k, v = _qkv(hkv=hkv)
    got = flash_attention(q, k, v, causal=causal)
    want = vanilla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_grads_match_dense(causal):
    q, k, v = _qkv(s=24, hkv=2, seed=1)

    def loss(attn):
        return lambda q, k, v: jnp.sum(attn(q, k, v, causal=causal) ** 2)

    g_f = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_v = jax.grad(loss(vanilla_attention), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_f, g_v):
        assert a.shape == b.shape, name  # dk/dv come back group-reduced
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, err_msg=f"d{name}"
        )


def test_flash_gqa_rejects_indivisible_heads():
    q, k, v = _qkv(h=4, hkv=3)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, v)


def test_causal_lm_gqa_params_and_training():
    """heads_kv builds split q/kv projections (smaller than fused qkv) and
    the model still learns the retrieval task."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="gqa_lm", model="causal_lm",
        model_kwargs={"dim": 64, "depth": 2, "heads": 4, "heads_kv": 2,
                      "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 64},
        n_train=2048, n_test=64, batch_size=64, epochs=8, lr=3e-3,
        quiet=True, eval_batch_size=32, eval_every=8,
    )
    t = Trainer(cfg)
    blk = t.state.params["block_0"]
    assert "q_proj" in blk and "kv_proj" in blk and "qkv" not in blk
    assert blk["q_proj"]["kernel"].shape == (64, 64)
    assert blk["kv_proj"]["kernel"].shape == (64, 2 * 2 * 16)  # half the kv
    t.fit()
    assert t.history[-1]["train_loss"] < 2.0


def test_gqa_decode_teacher_forcing():
    """The heads_kv-sized KV cache decodes to the same logits as the full
    forward."""
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    model = get_model("causal_lm", num_classes=16, dim=64, depth=2, heads=4,
                      heads_kv=2, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 16, size=(2, 12)), jnp.int32)
    full = model.apply({"params": params}, tokens)
    logits, vars_ = model.apply(
        {"params": params}, tokens[:, :6], decode=True, max_len=12,
        mutable=["cache"],
    )
    assert vars_["cache"]["block_0"]["k"].shape == (2, 12, 2, 16)  # hkv=2
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :6]),
                               atol=2e-4)
    cache = vars_["cache"]
    for t in range(6, 12):
        step, vars_ = model.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            decode=True, max_len=12, mutable=["cache"],
        )
        cache = vars_["cache"]
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(full[:, t]), atol=2e-4)


def test_gqa_ring_sp_matches_single_device(eight_devices):
    """GQA under ring sequence parallelism: k/v shards carry heads_kv heads
    around the ring; trajectory matches the unsharded run."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="causal_lm",
        model_kwargs={"dim": 64, "depth": 1, "heads": 4, "heads_kv": 2,
                      "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 64},
        n_train=256, n_test=64, batch_size=64, epochs=2, quiet=True,
        eval_batch_size=32,
    )
    t1 = Trainer(RunConfig(name="gqa1", **base))
    t1.fit()
    tsp = Trainer(RunConfig(name="gqasp", dp=2, sp=4, sp_impl="ring", **base))
    tsp.fit()
    a, b = jax.device_get((t1.state.params, tsp.state.params))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=2e-3)


def test_gqa_ulysses_validation(eight_devices):
    """Ulysses must split heads_kv too: heads_kv=2 with sp=4 is refused."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="gqau", model="causal_lm",
        model_kwargs={"dim": 64, "depth": 1, "heads": 4, "heads_kv": 2,
                      "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 64},
        n_train=256, n_test=64, batch_size=64, epochs=1, quiet=True,
        eval_batch_size=32, dp=2, sp=4, sp_impl="ulysses",
    )
    with pytest.raises(ValueError, match="heads_kv"):
        Trainer(cfg)
    # heads_kv=2 with sp=2 divides -> builds
    Trainer(cfg.replace(dp=4, sp=2))


def test_gqa_tp_shards_split_projections(eight_devices):
    """megatron_rule column-shards q_proj/kv_proj like the fused qkv."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="gqatp", model="causal_lm",
        model_kwargs={"dim": 64, "depth": 1, "heads": 4, "heads_kv": 2,
                      "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 64},
        n_train=256, n_test=64, batch_size=64, epochs=1, quiet=True,
        eval_batch_size=32, dp=4, tp=2,
    )
    t = Trainer(cfg)
    blk = t.state.params["block_0"]
    assert tuple(blk["q_proj"]["kernel"].sharding.spec) == (None, "model")
    assert tuple(blk["kv_proj"]["kernel"].sharding.spec) == (None, "model")
    s = t.fit()
    assert np.isfinite(s["best_test_accuracy"])

"""ZeRO-1 sharded weight update (ISSUE 1 tentpole).

Acceptance: the bucketed reduce-scatter + sharded-optimizer + all-gather
step reproduces the replicated step's loss trajectory exactly (per-step
allclose on the 8-device virtual mesh), the optimizer state really lives
1/N per device, and the fsdp opt-spec upgrade shards the moments the
min_size threshold used to keep replicated.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState
from distributed_tensorflow_ibm_mnist_tpu.core.optim import init_sharded_opt_state
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.collectives import (
    ShardedUpdate,
    flatten_buckets,
    make_bucket_layout,
    unflatten_buckets,
)
from distributed_tensorflow_ibm_mnist_tpu.parallel.data_parallel import (
    make_dp_epoch_runner,
    make_dp_train_step,
    place_sharded_update_state,
    replicate,
    shard_dataset,
)
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh


def _mlp_state(tx, hidden=(64,)):
    model = get_model("mlp", num_classes=10, hidden=hidden, dtype=jnp.float32)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    return model, state


def _batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.integers(0, 255, size=(n, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, size=(n,)).astype(np.int32)),
    }


@pytest.mark.quick
def test_bucket_layout_roundtrip_and_balance():
    """flatten -> unflatten is the identity; buckets are padded to the shard
    count and reasonably size-balanced."""
    tree = {
        "a": jnp.arange(100, dtype=jnp.float32).reshape(10, 10),
        "b": jnp.arange(7, dtype=jnp.float32),
        "c": {"k": jnp.ones((33, 3), jnp.float32), "v": jnp.zeros((5,), jnp.float32)},
    }
    lay = make_bucket_layout(tree, n_shards=8, n_buckets=2)
    assert all(s % 8 == 0 for s in lay.bucket_sizes)
    assert sum(lay.bucket_sizes) >= sum(x.size for x in jax.tree.leaves(tree))
    buckets = flatten_buckets(tree, lay)
    assert tuple(b.shape[0] for b in buckets) == lay.bucket_sizes
    back = unflatten_buckets(buckets, lay)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # largest-first greedy: no bucket holds everything when 2 were asked for
    assert len(lay.bucket_sizes) == 2
    assert min(lay.bucket_sizes) > 0


@pytest.mark.quick
def test_bucket_layout_mixed_dtypes_and_errors():
    tree = {"f": jnp.ones((16,), jnp.float32), "h": jnp.ones((8,), jnp.bfloat16)}
    lay = make_bucket_layout(tree, n_shards=4, n_buckets=2)
    # one bucket group per dtype; leaves never share a bucket across dtypes
    assert len(lay.bucket_sizes) == 2
    back = unflatten_buckets(flatten_buckets(tree, lay), lay)
    assert back["h"].dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="n_shards"):
        make_bucket_layout(tree, n_shards=0)
    with pytest.raises(ValueError, match="n_buckets"):
        make_bucket_layout(tree, n_shards=2, n_buckets=0)


def test_sharded_step_matches_replicated_with_clip(eight_devices):
    """The tentpole parity claim: bucketed reduce-scatter + 1/N update +
    all-gather walks the SAME trajectory as pmean + replicated update —
    including the global-norm clip, which the sharded step must compute
    from a cross-shard psum."""
    mesh = make_mesh(dp=8)
    clip = 1.0
    inner = lambda: optax.chain(optax.add_decayed_weights(1e-4), optax.adam(1e-3))
    tx = inner()
    tx_ref = optax.chain(optax.clip_by_global_norm(clip), inner())

    model, state = _mlp_state(tx)
    _, ref0 = _mlp_state(tx_ref)
    lay = make_bucket_layout(state.params, n_shards=8, n_buckets=3)
    su = ShardedUpdate(layout=lay, clip=clip)

    sh_state = state.replace(opt_state=init_sharded_opt_state(tx, state.params, lay))
    sh_state = place_sharded_update_state(mesh, sh_state, lay)
    ref_state = replicate(mesh, ref0)

    sh_step = make_dp_train_step(model, tx, mesh, sharded_update=su, state=sh_state)
    ref_step = make_dp_train_step(model, tx_ref, mesh)
    batch = _batch()
    for _ in range(3):
        sh_state, sh_m = sh_step(sh_state, batch)
        ref_state, ref_m = ref_step(ref_state, batch)
        np.testing.assert_allclose(
            float(sh_m["loss"]), float(ref_m["loss"]), rtol=1e-5
        )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)
    # the memory claim: every bucket leaf sharded over 'data', 1/8 per device
    sizes = set(lay.bucket_sizes)
    bucket_leaves = [
        leaf for leaf in jax.tree.leaves(sh_state.opt_state)
        if getattr(leaf, "ndim", 0) == 1 and leaf.size in sizes
    ]
    assert bucket_leaves, "no bucket-shaped optimizer leaves found"
    for leaf in bucket_leaves:
        assert leaf.sharding.spec == P("data")
        assert {s.data.size for s in leaf.addressable_shards} == {leaf.size // 8}


def test_sharded_epoch_runner_matches_replicated(eight_devices):
    """Whole-epoch parity: same per-step losses under the scan too (the
    dp_sharded_update acceptance criterion)."""
    mesh = make_mesh(dp=8)
    tx = optax.adam(1e-3)
    model, state = _mlp_state(tx)
    lay = make_bucket_layout(state.params, n_shards=8, n_buckets=2)
    su = ShardedUpdate(layout=lay)

    rng = np.random.default_rng(1)
    images = rng.integers(0, 255, size=(512, 28, 28, 1), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(512,)).astype(np.int32)
    imgs, labs = shard_dataset(mesh, images, labels)

    sh_state = state.replace(opt_state=init_sharded_opt_state(tx, state.params, lay))
    sh_state = place_sharded_update_state(mesh, sh_state, lay)
    # fresh buffers: device_put may alias the source arrays, and the
    # donating runners would delete the other leg's state out from under it
    rep_state = replicate(mesh, jax.tree.map(jnp.copy, state))

    run_sh = make_dp_epoch_runner(
        model, tx, 128, mesh, sharded_update=su, state=sh_state
    )
    run_rep = make_dp_epoch_runner(model, tx, 128, mesh)
    for epoch in range(2):
        key = jax.random.PRNGKey(epoch)
        sh_state, m_sh = run_sh(sh_state, imgs, labs, key)
        rep_state, m_rep = run_rep(rep_state, imgs, labs, key)
        np.testing.assert_allclose(
            np.asarray(m_sh["loss"]), np.asarray(m_rep["loss"]), rtol=2e-5
        )
    for a, b in zip(jax.tree.leaves(rep_state.params), jax.tree.leaves(sh_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
    assert int(jax.device_get(sh_state.step)) == 8


def test_trainer_config_driven_sharded_update(eight_devices):
    """RunConfig(sharded_update=True): same trajectory as the replicated
    trainer, opt buckets sharded, checkpoint round-trips through the
    gather-on-save path back into the sharded layout."""
    import tempfile

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="mlp", model_kwargs={"hidden": (64,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=64, epochs=1, lr=2e-3, quiet=True, seed=3,
        eval_batch_size=64, grad_clip=1.0,
    )
    with tempfile.TemporaryDirectory() as ckdir:
        t_s = Trainer(RunConfig(name="sh", dp=8, sharded_update=True,
                                checkpoint_dir=ckdir, **base))
        t_r = Trainer(RunConfig(name="rep", dp=8, **base))
        t_s.fit()
        t_r.fit()
        for a, b in zip(jax.tree.leaves(jax.device_get(t_s.state.params)),
                        jax.tree.leaves(jax.device_get(t_r.state.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)
        lay = t_s._dp_sharded.layout
        sizes = set(lay.bucket_sizes)
        bucket_leaves = [
            leaf for leaf in jax.tree.leaves(t_s.state.opt_state)
            if getattr(leaf, "ndim", 0) == 1 and leaf.size in sizes
        ]
        assert bucket_leaves and all(
            leaf.sharding.spec == P("data") for leaf in bucket_leaves
        )

        # restore into a fresh trainer: same opt values, sharded layout again
        t_2 = Trainer(RunConfig(name="sh", dp=8, sharded_update=True,
                                checkpoint_dir=ckdir, **base))
        assert t_2.restore_checkpoint() == int(jax.device_get(t_s.state.step))
        for a, b in zip(jax.tree.leaves(jax.device_get(t_s.state.opt_state)),
                        jax.tree.leaves(jax.device_get(t_2.state.opt_state))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        restored = [
            leaf for leaf in jax.tree.leaves(t_2.state.opt_state)
            if getattr(leaf, "ndim", 0) == 1 and leaf.size in sizes
        ]
        assert all(leaf.sharding.spec == P("data") for leaf in restored)


def test_fsdp_sharded_update_shards_small_leaf_moments(eight_devices):
    """fsdp + sharded_update: the moments of a min_size-replicated param
    (a (256,) bias — under fsdp_rule's 1024-element gather threshold) are
    sharded over 'data' anyway, and training still runs."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="fsdp_sh", model="mlp",
        model_kwargs={"hidden": (256,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=64, epochs=1, lr=1e-3, dp=8, fsdp=True, sharded_update=True,
        quiet=True, eval_batch_size=64,
    )
    t = Trainer(cfg)
    # the param itself stays replicated (gather-cost threshold)...
    assert t.state.params["dense_0"]["bias"].sharding.spec == P()
    # ...but its adam moments are sharded — the ZeRO-1 upgrade
    mu = t.state.opt_state[0].mu["dense_0"]["bias"]
    assert mu.sharding.spec == P("data")
    s = t.fit()
    assert s["epochs_run"] == 1
    assert np.isfinite(s["best_test_accuracy"])


@pytest.mark.quick
def test_sharded_update_validation():
    from distributed_tensorflow_ibm_mnist_tpu.core.steps import make_train_step

    with pytest.raises(ValueError, match="axis_name"):
        make_train_step(object(), optax.sgd(0.1), sharded_update=object())


def test_trainer_sharded_update_refusals(eight_devices):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(model="mlp", synthetic=True, n_train=128, n_test=32,
                batch_size=32, quiet=True)
    with pytest.raises(ValueError, match="dp>1"):
        Trainer(RunConfig(dp=1, sharded_update=True, **base))
    with pytest.raises(ValueError, match="sharded_update composes"):
        Trainer(RunConfig(dp=4, tp=2, sharded_update=True, **base))
    with pytest.raises(ValueError, match="sharded_update_buckets"):
        Trainer(RunConfig(dp=8, sharded_update=True,
                          sharded_update_buckets=0, **base))

"""Distributed trace-context unit suite (ISSUE 19 satellite).

Pins the W3C ``traceparent`` surface (:class:`TraceContext`), the
head+tail sampler, span links/annotation, the multi-tracer merge, the
forest connectivity checker, and the exemplar-bearing OpenMetrics
exposition — the building blocks the serving tier's end-to-end tracing
(scripts/bench_tracing.py) is assembled from.
"""

import io
import json

import pytest

from distributed_tensorflow_ibm_mnist_tpu.serving.frontend import (
    _sanitize_request_id,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import (
    MetricsRegistry,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
    TraceContext,
    Tracer,
    TraceSampler,
    merge_traces,
    trace_forest,
    validate_trace,
)

TID = "0af7651916cd43dd8448eb211c80319c"
SID = "b7ad6b7169203331"


# ----------------------------------------------------------------------
# TraceContext: mint / parse / round-trip


def test_mint_well_formed():
    ctx = TraceContext.mint()
    assert len(ctx.trace_id) == 32 and int(ctx.trace_id, 16) != 0
    assert len(ctx.span_id) == 16 and int(ctx.span_id, 16) != 0
    assert ctx.trace_id == ctx.trace_id.lower()
    assert ctx.sampled is True


def test_mint_unique():
    seen = {TraceContext.mint().trace_id for _ in range(64)}
    assert len(seen) == 64


def test_traceparent_round_trip():
    for sampled in (True, False):
        ctx = TraceContext(TID, SID, sampled=sampled)
        back = TraceContext.parse_traceparent(ctx.to_traceparent())
        assert back == ctx
        assert back.sampled is sampled


def test_to_traceparent_format():
    assert (TraceContext(TID, SID, sampled=True).to_traceparent()
            == f"00-{TID}-{SID}-01")
    assert (TraceContext(TID, SID, sampled=False).to_traceparent()
            == f"00-{TID}-{SID}-00")


def test_child_same_trace_fresh_span():
    ctx = TraceContext(TID, SID, sampled=False)
    kid = ctx.child()
    assert kid.trace_id == TID
    assert kid.span_id != SID
    assert kid.sampled is False


def test_ctor_rejects_bad_ids():
    with pytest.raises(ValueError):
        TraceContext("0" * 32, SID)          # all-zero trace id
    with pytest.raises(ValueError):
        TraceContext(TID, "0" * 16)          # all-zero span id
    with pytest.raises(ValueError):
        TraceContext(TID[:-1], SID)          # short
    with pytest.raises(ValueError):
        TraceContext(TID.upper(), SID)       # uppercase


@pytest.mark.parametrize("header", [
    None,
    "",
    "garbage",
    f"00-{TID}-{SID}",                       # missing flags
    f"00-{'0' * 32}-{SID}-01",               # all-zero trace id
    f"00-{TID}-{'0' * 16}-01",               # all-zero span id
    f"00-{TID.upper()}-{SID}-01",            # uppercase hex
    f"00-{TID}-{SID}-0g",                    # non-hex flags
    f"ff-{TID}-{SID}-01",                    # forbidden version
    f"00-{TID}-{SID}-01-extra",              # v00 must have exactly 4
    f"0-{TID}-{SID}-01",                     # short version
    f"00-{TID[:-2]}-{SID}-01",               # short trace id
])
def test_parse_rejects(header):
    assert TraceContext.parse_traceparent(header) is None


def test_parse_future_version_tolerant():
    # a future version may append fields — first four still parse
    ctx = TraceContext.parse_traceparent(f"cc-{TID}-{SID}-01-what-ever")
    assert ctx is not None and ctx.trace_id == TID and ctx.sampled


def test_parse_honors_flags():
    assert TraceContext.parse_traceparent(f"00-{TID}-{SID}-00").sampled is False
    assert TraceContext.parse_traceparent(f"00-{TID}-{SID}-01").sampled is True


# ----------------------------------------------------------------------
# TraceSampler: head determinism + tail always-keep


def test_head_extremes_and_determinism():
    assert TraceSampler(rate=1.0).head(TID) is True
    assert TraceSampler(rate=0.0).head(TID) is False
    s = TraceSampler(rate=0.5)
    assert s.head(TID) == s.head(TID)
    # deterministic on the id prefix: low prefix in, high prefix out
    assert s.head("0" * 7 + "1" + "0" * 24) is True
    assert s.head("f" * 32) is False


def test_bad_rate_rejected():
    with pytest.raises(ValueError):
        TraceSampler(rate=1.5)
    with pytest.raises(ValueError):
        TraceSampler(rate=-0.1)


def test_tail_keep_rules():
    s = TraceSampler(rate=0.0)
    assert s.keep([{"name": "x", "args": {"status": "failed"}}])
    assert s.keep([{"name": "x", "args": {"status": "cancelled"}}])
    assert s.keep([{"name": "shed", "args": {}}])
    assert s.keep([{"name": "x", "args": {"slo_miss": True}}])
    assert s.keep([{"name": "x", "args": {"error": "boom"}}])
    assert s.keep([{"name": "x", "args": {"sampled": True}}])  # head verdict
    assert not s.keep([{"name": "x", "args": {"status": "done"}}])


# ----------------------------------------------------------------------
# annotate + links + sampled export


def _one_trace(tr, trace_id, status="done", sampled=True):
    root = tr.begin("request", trace=trace_id, sampled=sampled)
    child = tr.begin("work", parent=root)
    tr.end(child)
    tr.end(root, status=status)
    return root


def test_annotate_reparent_links_args():
    tr = Tracer()
    a = tr.begin("attempt0")
    b = tr.begin("attempt1")
    assert tr.annotate(b, parent=a, links=[a], replica=3) is True
    tr.end(b)
    tr.end(a)
    evs = {e["name"]: e for e in tr.events()}
    assert evs["attempt1"]["parent"] == a
    assert evs["attempt1"]["args"]["links"] == [a]
    assert evs["attempt1"]["args"]["replica"] == 3


def test_annotate_closed_span_is_noop():
    tr = Tracer()
    a = tr.begin("x")
    tr.end(a)
    assert tr.annotate(a, status="late") is False


def test_links_survive_export_and_validate(tmp_path):
    tr = Tracer()
    a = tr.begin("attempt0", trace=TID, sampled=True)
    tr.end(a, status="failed")
    b = tr.begin("attempt1", trace=TID, sampled=True)
    tr.annotate(b, links=[a])
    tr.end(b, status="done")
    path = str(tmp_path / "t.json")
    tr.export_trace(path)
    assert validate_trace(path) == []
    doc = json.load(open(path))
    linked = [e for e in doc["traceEvents"]
              if e.get("args", {}).get("links")]
    assert len(linked) == 1


def test_sampler_filters_whole_trace_groups(tmp_path):
    tr = Tracer()
    _one_trace(tr, "aa" * 16, sampled=False)              # dropped
    _one_trace(tr, "bb" * 16, sampled=True)               # head-kept
    _one_trace(tr, "cc" * 16, status="failed", sampled=False)  # tail-kept
    path = str(tmp_path / "s.json")
    tr.export_trace(path, sampler=TraceSampler(rate=0.0))
    assert validate_trace(path) == []
    traces = {e.get("args", {}).get("trace")
              for e in json.load(open(path))["traceEvents"]}
    assert "aa" * 16 not in traces
    assert "bb" * 16 in traces and "cc" * 16 in traces


def test_trace_events_closure():
    tr = Tracer()
    root = tr.begin("request", trace=TID)
    child = tr.begin("work", parent=root)
    tr.instant("mark", parent=child)
    tr.end(child)
    tr.end(root)
    _one_trace(tr, "dd" * 16)   # unrelated
    evs = tr.trace_events(TID)
    assert {e["name"] for e in evs} == {"request", "work", "mark"}


# ----------------------------------------------------------------------
# merge + forest


def test_merge_connects_processes_and_forest_agrees(tmp_path):
    front, back = Tracer(), Tracer()
    f_root = front.begin("http_request", trace=TID, sampled=True,
                         span_ctx=SID)
    front.end(f_root, status="done")
    b_root = back.begin("daemon_request", trace=TID, parent_ctx=SID)
    b_child = back.begin("work", parent=b_root)
    back.end(b_child)
    back.end(b_root, status="done")
    path = str(tmp_path / "m.json")
    doc = merge_traces([front, back], path, names=["front", "back"])
    assert validate_trace(path) == []
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(pids) == 2
    forest = trace_forest(doc)
    g = forest[TID]
    assert g["connected"] is True
    assert g["spans"] == 3
    assert {"http_request", "daemon_request", "work"} <= set(g["names"])


def test_forest_flags_disconnected():
    tr = Tracer()
    a = tr.begin("island_a", trace=TID)
    tr.end(a)
    b = tr.begin("island_b", trace=TID)   # same trace id, no edge
    tr.end(b)
    g = trace_forest(tr.to_doc())[TID]
    assert g["connected"] is False
    assert len(g["roots"]) == 2


def test_merge_into_buffer():
    tr = Tracer()
    _one_trace(tr, TID)
    buf = io.StringIO()
    merge_traces([tr], buf)
    assert json.loads(buf.getvalue())["traceEvents"]


# ----------------------------------------------------------------------
# exemplars / OpenMetrics


def test_openmetrics_exemplars_and_shape():
    reg = MetricsRegistry()
    reg.inc("requests", 3)
    reg.set_gauge("depth", 2.0)
    reg.observe("ttft_s", 0.01, exemplar=TID)
    reg.observe("ttft_s", 123456.0, exemplar="ee" * 16)  # overflow bucket
    text = reg.to_openmetrics()
    assert text.rstrip().endswith("# EOF")
    assert "dtm_requests_total 3" in text
    lines = [l for l in text.splitlines() if " # {" in l]
    assert any(f'trace_id="{TID}"' in l for l in lines)
    inf = [l for l in lines if 'le="+Inf"' in l]
    assert inf and 'trace_id="' + "ee" * 16 + '"' in inf[0]
    # classic exposition unchanged — no exemplar syntax leaks in
    assert " # {" not in reg.to_prometheus()


def test_exemplar_none_is_fine():
    reg = MetricsRegistry()
    reg.observe("x_s", 0.5)
    reg.observe("x_s", 0.5, exemplar=None)
    assert 'le="+Inf"' in reg.to_openmetrics()


# ----------------------------------------------------------------------
# front-door request-id sanitizer (satellite 2)


@pytest.mark.parametrize("raw,want", [
    ("abc-123", "abc-123"),
    ("A.b:c_d-9", "A.b:c_d-9"),
    ("x" * 64, "x" * 64),
    ("x" * 65, None),          # over the cap
    ("", None),
    (None, None),
    ("has space", None),
    ("new\r\nline: inject", None),
    ("héllo", None),
    (123, None),
])
def test_sanitize_request_id(raw, want):
    assert _sanitize_request_id(raw) == want

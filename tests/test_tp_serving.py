"""Tensor-parallel serving (ISSUE 10): sharding is invisible in the tokens.

The decisive properties:

* PARITY — a curated slice of the composition matrix ({dense, paged} x
  {native, int8 KV} x decode_ahead ∈ {1, 8} x {plain, speculative}) at
  tp ∈ {2, 4} is token-identical to the same config at tp=1: GSPMD
  partitioning (Megatron column/row splits + the KV head-axis shard)
  changes what each chip holds, never what the model says.
* MEMORY — per-chip weight and KV bytes land at ~1/tp of the tp=1
  figure in BOTH cache layouts, and ``ServingStats`` carries
  tp/kv_bytes_per_chip/weight_bytes_per_chip through ``merge`` into the
  router rollup (strict JSON: None, never NaN).
* LAUNCH/OPS — ``prewarm()`` under a tp mesh compiles the whole family
  so subsequent serving compiles ZERO programs; ``swap_params`` accepts
  a full HOST param tree and re-shards it; chaos event counts at
  ``serving-admit``/``serving-step`` are tp-invariant (the host control
  loop is layout-blind); a 2-replica router over disjoint 2-chip tp
  groups survives a mid-wave replica kill token-identically.

The whole file runs on the 8-virtual-CPU-device platform tests/
conftest.py arms (``eight_devices`` skips otherwise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    tp_device_groups,
)
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    Router,
    ServingStats,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)

KW = dict(num_classes=16, dim=64, depth=2, heads=4, dtype=jnp.float32)

MAX_LEN = 32
# repetitive suffixes so the speculative cases' n-gram drafter gets hits
PROMPTS = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 5, 4, 5, 4, 5], [6, 7, 8, 9],
           [2, 4, 2, 4, 2, 4]]


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, tp=1, **ekw):
    return InferenceEngine(
        model, params, slots=2, max_len=MAX_LEN, tp=tp,
        scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,),
                                max_queue=len(PROMPTS)),
        **ekw)


def _serve(model, params, tp=1, max_new=6, prompts=PROMPTS, **ekw):
    eng = _engine(model, params, tp=tp, **ekw)
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.run()
    outs = [list(r.generated) for r in reqs]
    eng.close()
    return outs


@pytest.fixture(scope="module")
def native(eight_devices):
    return _model_and_params()


@pytest.fixture(scope="module")
def int8(eight_devices):
    return _model_and_params(kv_cache_dtype="int8")


@pytest.fixture(scope="module")
def refs(native, int8):
    """tp=1 greedy output per KV dtype — dense/paged/k/spec invariance at
    tp=1 is already pinned by test_serving/test_kv_paging/
    test_speculative, so one dense reference per dtype suffices."""
    return {
        "native": _serve(*native, tp=1),
        "int8": _serve(*int8, tp=1),
    }


# ----------------------------------------------------------------------
# parity: the curated composition slice


CASES = [
    # (tp, kv_dtype, paged, decode_ahead, speculative)
    (2, "native", False, 1, False),
    (2, "native", True, 1, False),
    (2, "int8", False, 8, False),
    (2, "native", True, 8, True),
    (4, "native", False, 8, False),
    (4, "int8", True, 1, False),
    (4, "native", False, 1, True),
    (4, "native", True, 8, False),
]


@pytest.mark.parametrize(
    "tp,kvd,paged,k,spec", CASES,
    ids=[f"tp{t}-{d}-{'paged' if p else 'dense'}-k{k}-"
         f"{'spec' if s else 'plain'}" for t, d, p, k, s in CASES])
def test_tp_parity(native, int8, refs, tp, kvd, paged, k, spec):
    model, params = native if kvd == "native" else int8
    ekw = {"decode_ahead": k}
    if paged:
        ekw["kv_page_size"] = 8
    if spec:
        ekw.update(speculative="ngram", draft_len=3)
    assert _serve(model, params, tp=tp, **ekw) == refs[kvd]


# ----------------------------------------------------------------------
# memory: per-chip bytes 1/tp in both layouts, stats plumbing


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_per_chip_bytes_drop_by_tp(native, paged):
    model, params = native
    ekw = {"kv_page_size": 8} if paged else {}
    sizes = {}
    for tp in (1, 2, 4):
        eng = _engine(model, params, tp=tp, **ekw)
        sizes[tp] = (eng.weight_bytes_per_chip(), eng.kv_bytes_per_chip())
        s = eng.stats.summary()
        assert s["tp"] == tp
        assert s["kv_bytes_per_chip"] == sizes[tp][1]
        assert s["weight_bytes_per_chip"] == sizes[tp][0]
        eng.close()
    for tp in (2, 4):
        w_ratio = sizes[1][0] / sizes[tp][0]
        kv_ratio = sizes[1][1] / sizes[tp][1]
        # embeddings/logits replicate (weights) and the paged block
        # table/index replicate (KV) — the honest tax inside ±10%
        assert 0.9 * tp <= w_ratio <= 1.1 * tp, (tp, w_ratio)
        assert 0.9 * tp <= kv_ratio <= 1.1 * tp, (tp, kv_ratio)


def test_stats_memory_merges_into_rollup(eight_devices):
    """merge: homogeneous tp survives, per-chip = max, cluster = sum of
    per_chip * tp; unstamped engines -> None (never NaN); mixed tp ->
    tp None.  Strict JSON end to end."""
    import json

    a, b = ServingStats(2), ServingStats(2)
    a.memory(tp=2, kv_bytes_per_chip=100, weight_bytes_per_chip=1000)
    b.memory(tp=2, kv_bytes_per_chip=80, weight_bytes_per_chip=1000)
    m = ServingStats.merge([a, b])
    assert m["tp"] == 2
    assert m["kv_bytes_per_chip"] == 100          # worst chip anywhere
    assert m["kv_bytes_cluster"] == (100 + 80) * 2
    assert m["weight_bytes_cluster"] == 2000 * 2
    json.dumps(m)  # strict JSON (raises on NaN/inf by default upcast)

    c = ServingStats(2)  # never stamped
    m2 = ServingStats.merge([c])
    assert m2["kv_bytes_per_chip"] is None
    assert m2["kv_bytes_cluster"] is None
    b.memory(tp=4, kv_bytes_per_chip=80, weight_bytes_per_chip=1000)
    assert ServingStats.merge([a, b])["tp"] is None  # heterogeneous


# ----------------------------------------------------------------------
# launch/ops under the mesh


def test_prewarm_under_tp_then_zero_serving_compiles(native):
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        CompileTracker,
    )

    model, params = native
    tracker = CompileTracker.install()
    eng = _engine(model, params, tp=2)
    eng.prewarm()
    before = tracker.snapshot()
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    d = CompileTracker.delta(tracker.snapshot(), before)
    assert d["n_compiled_programs"] == 0, d["by_site"]
    assert all(r.status == "done" for r in reqs)
    eng.close()


def test_swap_params_reshards_host_tree_under_tp(native, refs):
    """swap_params at tp=2 with a full HOST (numpy) tree from a different
    seed: the engine re-shards it wholesale and serves the new weights'
    tokens (pinned against a tp=1 engine built on those weights)."""
    model, params = native
    model2, params2 = _model_and_params(seed=3)
    want2 = _serve(model2, params2, tp=1)

    eng = _engine(model, params, tp=2)
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    assert [list(r.generated) for r in reqs] == refs["native"]
    host_tree = jax.tree.map(np.asarray, jax.device_get(params2))
    eng.swap_params(host_tree)
    leaf = jax.tree.leaves(eng.params)[0]
    assert "tp" in str(leaf.sharding)  # re-sharded, not host-resident
    reqs2 = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    assert [list(r.generated) for r in reqs2] == want2
    eng.close()


def test_chaos_event_counts_tp_invariant(native):
    """The chaos clock (one serving-admit per admission attempt, one
    serving-step per window dispatch) ticks in the HOST control loop —
    sharding the device programs must not move a single event."""
    model, params = native
    counts = {}
    for tp in (1, 2, 4):
        inj = FaultInjector(FaultPlan(faults=()))
        eng = _engine(model, params, tp=tp, chaos=inj)
        for p in PROMPTS:
            eng.submit(p, max_new=6)
        eng.run()
        eng.close()
        counts[tp] = (inj.events("serving-admit"),
                      inj.events("serving-step"))
    assert counts[1] == counts[2] == counts[4], counts
    assert counts[1][0] >= len(PROMPTS) and counts[1][1] > 0


def test_router_failover_over_disjoint_tp_groups(native, refs):
    """2 replicas x disjoint 2-chip groups (two-parameter factory:
    make_engine(tid, replica_index) -> tp_devices=groups[index]); chaos
    kills replica decode mid-wave; the wave finishes token-identical
    with exactly one failover."""
    model, params = native
    groups = tp_device_groups(2, 2)
    assert len(groups) == 2 and not set(groups[0]) & set(groups[1])
    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-step", kind="transient", at=(1,)),)))

    def make_engine(tid, index):
        return InferenceEngine(
            model, params, slots=2, max_len=MAX_LEN, tp=2,
            tp_devices=groups[index],
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,),
                                    max_queue=len(PROMPTS)),
            trace_tid=tid, chaos=inj, stall_timeout_s=None)

    with Router(make_engine, 2) as r:
        rrs = [r.submit(p, max_new=6) for p in PROMPTS]
        r.run_until_done()
        assert [list(rr.generated) for rr in rrs] == refs["native"]
        assert all(rr.status == "done" for rr in rrs)
        assert r.failovers == 1
        summ = r.summary()
        assert summ["tp"] == 2
        assert summ["kv_bytes_cluster"] is not None


# ----------------------------------------------------------------------
# quant x tp (ISSUE 12 satellite): int8 weights shard like their f32
# ancestors, scales ride the Megatron split, tokens never move


@pytest.fixture(scope="module")
def quant_ref(native):
    return _serve(*native, tp=1, quant="int8")


@pytest.mark.parametrize("tp", [2, 4])
def test_quant_tp_parity_and_scale_layout(native, quant_ref, tp):
    """quant engine at tp=N: token-identical to the tp=1 quant engine,
    int8 kernels column/row-sharded, column scales P('tp') and row
    scales replicated (per-output scale is uniform over the contraction
    axis, so it distributes over the psum)."""
    model, params = native
    eng = _engine(model, params, tp=tp, quant="int8")
    blk = eng.params["block_0"]
    assert blk["qkv"]["kernel"].dtype == jnp.int8
    assert "tp" in str(blk["qkv"]["scale"].sharding.spec)     # column
    assert "tp" not in str(blk["proj"]["scale"].sharding.spec)  # row
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    assert [list(r.generated) for r in reqs] == quant_ref
    eng.close()


def test_quant_per_chip_weight_bytes(native):
    """Per-chip weight bytes: ~4x smaller than f32 at tp=1 (kernels go
    4 -> 1 byte; embed/norms/biases stay f32), and still ~1/tp under
    the mesh — the int8 tree sharded like any other."""
    model, params = native
    sizes = {}
    for tp in (1, 2, 4):
        eng = _engine(model, params, tp=tp, quant="int8")
        sizes[tp] = eng.weight_bytes_per_chip()
        assert eng.stats.summary()["quant"] == "int8"
        eng.close()
    feng = _engine(model, params, tp=1)
    full = feng.weight_bytes_per_chip()
    feng.close()
    assert 3.2 <= full / sizes[1] <= 4.0, (full, sizes[1])
    for tp in (2, 4):
        ratio = sizes[1] / sizes[tp]
        # replicated embed/logits-scale tax is proportionally LARGER on
        # the int8 tree, so the floor is looser than the f32 case
        assert 0.45 * tp <= ratio <= 1.1 * tp, (tp, ratio)


@pytest.mark.parametrize("tp", [1, 2, 4])
def test_quant_swap_requantizes_and_reshards(native, tp):
    """swap_params with a full-precision HOST tree at each tp: the
    engine re-quantizes AND re-shards at the seam, pinned against a
    fresh tp=1 quant engine on those weights."""
    model, params = native
    model2, params2 = _model_and_params(seed=3)
    want2 = _serve(model2, params2, tp=1, quant="int8")

    eng = _engine(model, params, tp=tp, quant="int8")
    host_tree = jax.tree.map(np.asarray, jax.device_get(params2))
    eng.swap_params(host_tree)
    assert eng.params["block_0"]["qkv"]["kernel"].dtype == jnp.int8
    if tp > 1:
        assert "tp" in str(
            eng.params["block_0"]["qkv"]["kernel"].sharding)
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    assert [list(r.generated) for r in reqs] == want2
    eng.close()


def test_chaos_event_counts_quant_invariant(native):
    """quant changes the device programs' dtypes, never the host
    control loop: admit/step event counts match the full-precision
    engine exactly at tp 1 and 2."""
    model, params = native
    counts = {}
    for quant in (None, "int8"):
        for tp in (1, 2):
            inj = FaultInjector(FaultPlan(faults=()))
            eng = _engine(model, params, tp=tp, quant=quant, chaos=inj)
            for p in PROMPTS:
                eng.submit(p, max_new=6)
            eng.run()
            eng.close()
            counts[(quant, tp)] = (inj.events("serving-admit"),
                                   inj.events("serving-step"))
    assert counts[(None, 1)] == counts[("int8", 1)] == counts[("int8", 2)]
    assert counts[(None, 1)][0] >= len(PROMPTS)


def test_router_failover_quant_token_identical(native, quant_ref):
    """2 quant replicas over disjoint 2-chip tp groups; chaos kills one
    mid-wave; the wave finishes on the quant reference tokens with
    exactly one failover, and the rollup reports quant."""
    model, params = native
    groups = tp_device_groups(2, 2)
    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-step", kind="transient", at=(1,)),)))

    def make_engine(tid, index):
        return InferenceEngine(
            model, params, slots=2, max_len=MAX_LEN, tp=2,
            tp_devices=groups[index], quant="int8",
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,),
                                    max_queue=len(PROMPTS)),
            trace_tid=tid, chaos=inj, stall_timeout_s=None)

    with Router(make_engine, 2) as r:
        rrs = [r.submit(p, max_new=6) for p in PROMPTS]
        r.run_until_done()
        assert [list(rr.generated) for rr in rrs] == quant_ref
        assert r.failovers == 1
        summ = r.summary()
        assert summ["quant"] == "int8"
        assert summ["tp"] == 2


def test_tp_must_divide_heads_whole(native):
    model, params = native
    with pytest.raises(ValueError, match="divide"):
        _engine(model, params, tp=3)
    gmodel, gparams = _model_and_params(heads_kv=2)
    with pytest.raises(ValueError, match="divide"):
        _engine(gmodel, gparams, tp=4)  # 4 does not divide heads_kv=2

"""The tracing-contract lint as a tier-1 test (ISSUE 19 satellite).

``scripts/lint_tracing.py`` enforces two mechanical invariants over the
serving package — every ``_tracer`` call is nil-guarded (zero-cost-off)
and no serving code reads ``time.time()`` (monotonic clock domain,
journal.py excepted).  Running it from pytest makes a regression a RED
test, not a forgotten CI step; the unit cases below pin that the checker
itself still catches what it claims to catch.
"""

import importlib.util
import os
import sys

import pytest

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _load():
    spec = importlib.util.spec_from_file_location(
        "lint_tracing", os.path.join(_SCRIPTS, "lint_tracing.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


lint = _load()


# ----------------------------------------------------------------------
# the real gate: the serving package is clean


def test_serving_package_is_clean():
    violations = lint.check_serving()
    assert violations == [], "\n".join(violations)


# ----------------------------------------------------------------------
# the checker catches what it claims to catch


def test_flags_unguarded_tracer_call():
    src = ("class E:\n"
           "    def f(self):\n"
           "        self._tracer.begin('x')\n")
    out = lint.check_source(src, "mod.py")
    assert len(out) == 1 and "unguarded tracer call" in out[0]


def test_accepts_if_not_none_body():
    src = ("class E:\n"
           "    def f(self):\n"
           "        if self._tracer is not None:\n"
           "            self._tracer.begin('x')\n")
    assert lint.check_source(src, "mod.py") == []


def test_accepts_conjoined_guard():
    src = ("class E:\n"
           "    def f(self, req):\n"
           "        if self._tracer is not None and req.trace is not None:\n"
           "            self._tracer.instant('x')\n")
    assert lint.check_source(src, "mod.py") == []


def test_accepts_early_return_guard():
    src = ("class E:\n"
           "    def f(self, t):\n"
           "        if self._tracer is None or t is None:\n"
           "            return\n"
           "        self._tracer.end(t)\n")
    assert lint.check_source(src, "mod.py") == []


def test_rejects_wrong_branch():
    # the call sits in the `is None` BODY — exactly backwards
    src = ("class E:\n"
           "    def f(self):\n"
           "        if self._tracer is None:\n"
           "            self._tracer.begin('x')\n")
    out = lint.check_source(src, "mod.py")
    assert len(out) == 1


def test_accepts_else_branch_of_is_none():
    src = ("class E:\n"
           "    def f(self):\n"
           "        if self._tracer is None:\n"
           "            pass\n"
           "        else:\n"
           "            self._tracer.begin('x')\n")
    assert lint.check_source(src, "mod.py") == []


def test_early_return_must_precede_the_call():
    src = ("class E:\n"
           "    def f(self):\n"
           "        self._tracer.begin('x')\n"
           "        if self._tracer is None:\n"
           "            return\n")
    out = lint.check_source(src, "mod.py")
    assert len(out) == 1


def test_accepts_conditional_expression_and_derived_guard():
    # the engine's prefill-span idiom: assign under an IfExp guard, then
    # close under `if span is not None:`
    src = ("class E:\n"
           "    def f(self, req):\n"
           "        span = (self._tracer.begin('prefill')\n"
           "                if self._tracer is not None"
           " and req.trace is not None else None)\n"
           "        try:\n"
           "            pass\n"
           "        finally:\n"
           "            if span is not None:\n"
           "                self._tracer.end(span)\n")
    assert lint.check_source(src, "mod.py") == []


def test_flags_wall_clock_in_serving():
    src = ("import time\n"
           "def f():\n"
           "    return time.time()\n")
    out = lint.check_source(src, "engine.py")
    assert len(out) == 1 and "time.time()" in out[0]


def test_wall_clock_allowlisted_for_journal():
    src = ("import time\n"
           "def f():\n"
           "    return time.time()\n")
    assert lint.check_source(src, "journal.py") == []


def test_monotonic_is_fine():
    src = ("import time\n"
           "def f():\n"
           "    return time.monotonic()\n")
    assert lint.check_source(src, "engine.py") == []


def test_cli_exit_status():
    import subprocess
    r = subprocess.run([sys.executable,
                        os.path.join(_SCRIPTS, "lint_tracing.py")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 violation(s)" in r.stdout


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))

"""Native C++ data pipeline: gather/renderer/prefetcher vs numpy truth.

These tests compile the library on first run (cached after).  If no C++
toolchain exists, the bindings must fall back silently — exercised by the
DTM_DISABLE_NATIVE path test.
"""

import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.data import native
from distributed_tensorflow_ibm_mnist_tpu.data.synthetic import (
    _DIGIT_GLYPHS,
    _glyphs_to_array,
    _make_split,
)

needs_native = pytest.mark.skipif(not native.available(), reason="no C++ toolchain")


@needs_native
def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 255, size=(500, 28, 28, 1), dtype=np.uint8)
    idx = rng.permutation(500)[:128].astype(np.int32)
    got = native.gather(src, idx, threads=4)
    np.testing.assert_array_equal(got, np.take(src, idx, axis=0))


@needs_native
def test_gather_float_rows():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(100, 17)).astype(np.float32)
    idx = rng.integers(0, 100, size=64).astype(np.int32)
    np.testing.assert_array_equal(native.gather(src, idx), src[idx])


@needs_native
def test_render_deterministic_and_thread_independent():
    templates = _glyphs_to_array(_DIGIT_GLYPHS)
    labels = np.arange(40, dtype=np.int32) % 10
    kw = dict(
        out_hw=(28, 28), scale_range=(2.2, 3.4), rot_range=0.3,
        shift_frac=0.12, noise_std=0.18, seed=7,
    )
    a = native.render_affine(templates, labels, threads=1, **kw)
    b = native.render_affine(templates, labels, threads=8, **kw)
    np.testing.assert_array_equal(a, b)  # per-sample streams: thread-invariant
    c = native.render_affine(templates, labels, threads=4, **kw)
    np.testing.assert_array_equal(a, c)


@needs_native
def test_render_produces_learnable_digits():
    """Sanity on the rendered distribution: ink where expected, classes differ."""
    templates = _glyphs_to_array(_DIGIT_GLYPHS)
    labels = np.repeat(np.arange(10, dtype=np.int32), 20)
    imgs = native.render_affine(
        templates, labels, out_hw=(28, 28), scale_range=(2.2, 3.4),
        rot_range=0.3, shift_frac=0.12, noise_std=0.18, seed=0,
    )
    assert imgs.shape == (200, 28, 28, 1) and imgs.dtype == np.uint8
    ink = imgs.astype(np.float32).mean(axis=(1, 2, 3))
    assert 10.0 < ink.mean() < 120.0  # neither blank nor saturated
    # per-class mean images must be mutually distinguishable
    means = np.stack([imgs[labels == c].mean(axis=0).ravel() for c in range(10)])
    d = np.linalg.norm(means[:, None] - means[None, :], axis=-1)
    assert (d + np.eye(10) * 1e9).min() > 50.0


@needs_native
def test_make_split_native_backend():
    templates = _glyphs_to_array(_DIGIT_GLYPHS)
    kw = dict(
        out_hw=(28, 28), scale_range=(2.2, 3.4), rot_range=0.3,
        shift_frac=0.12, noise_std=0.18,
    )
    x, y = _make_split(templates, 64, seed=3, backend="native", **kw)
    x2, y2 = _make_split(templates, 64, seed=3, backend="native", **kw)
    np.testing.assert_array_equal(x, x2)
    np.testing.assert_array_equal(y, y2)
    # same labels as the numpy backend (labels come from the shared stream)
    _, y_np = _make_split(templates, 64, seed=3, backend="numpy", **kw)
    np.testing.assert_array_equal(y, y_np)
    assert x.shape == (64, 28, 28, 1) and x.dtype == np.uint8


@needs_native
def test_prefetcher_matches_order():
    rng = np.random.default_rng(2)
    images = rng.integers(0, 255, size=(300, 8, 8, 1), dtype=np.uint8)
    labels = rng.integers(0, 10, size=300).astype(np.int32)
    perm = rng.permutation(300).astype(np.int32)[:256]
    batch = 32
    with native.Prefetcher(images, labels, batch, perm, depth=3, threads=3) as pf:
        got = list(pf)
    assert len(got) == 8
    for b, (img, lab) in enumerate(got):
        idx = perm[b * batch : (b + 1) * batch]
        np.testing.assert_array_equal(img, images[idx])
        np.testing.assert_array_equal(lab, labels[idx])


def test_fallback_without_native(monkeypatch):
    """With the library disabled, every entry point still works via numpy."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    rng = np.random.default_rng(3)
    src = rng.integers(0, 255, size=(50, 4), dtype=np.uint8)
    idx = np.arange(10, dtype=np.int32)
    np.testing.assert_array_equal(native.gather(src, idx), src[:10])
    assert native.render_affine(
        np.zeros((10, 7, 5), np.float32), idx, (28, 28), (2.0, 3.0), 0.3, 0.1, 0.1, 0
    ) is None
    labels = rng.integers(0, 10, size=50).astype(np.int32)
    perm = np.arange(48, dtype=np.int32)
    with native.Prefetcher(src, labels, 16, perm) as pf:
        got = list(pf)
    assert len(got) == 3
    np.testing.assert_array_equal(got[1][0], src[16:32])

"""Core engine: train step, epoch runner, eval fn."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_ibm_mnist_tpu.core import (
    TrainState,
    make_epoch_runner,
    make_eval_fn,
    make_train_step,
)
from distributed_tensorflow_ibm_mnist_tpu.data import synthetic_mnist
from distributed_tensorflow_ibm_mnist_tpu.models import get_model


def _tiny_setup(model_name="mlp", n=512, dtype=jnp.float32, **model_kwargs):
    data = synthetic_mnist(n_train=n, n_test=128, seed=0)
    model = get_model(model_name, num_classes=10, dtype=dtype, **model_kwargs)
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    return data, model, tx, state


def test_train_step_reduces_loss():
    data, model, tx, state = _tiny_setup()
    step = jax.jit(make_train_step(model, tx))
    imgs = jnp.asarray(data["train_images"][:64])
    labs = jnp.asarray(data["train_labels"][:64])
    batch = {"image": imgs, "label": labs}
    _, first = step(state, batch)
    for _ in range(50):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])
    assert float(metrics["accuracy"]) > 0.8


def test_train_step_increments_step_counter():
    _, model, tx, state = _tiny_setup(n=64)
    step = jax.jit(make_train_step(model, tx))
    batch = {
        "image": jnp.zeros((8, 28, 28, 1), jnp.uint8),
        "label": jnp.zeros((8,), jnp.int32),
    }
    state, _ = step(state, batch)
    state, _ = step(state, batch)
    assert int(state.step) == 2


def test_epoch_runner_runs_and_learns():
    data, model, tx, state = _tiny_setup(n=1024)
    run_epoch = jax.jit(make_epoch_runner(model, tx, batch_size=64))
    imgs = jnp.asarray(data["train_images"])
    labs = jnp.asarray(data["train_labels"])
    for epoch in range(4):
        state, metrics = run_epoch(state, imgs, labs, jax.random.PRNGKey(epoch))
    assert metrics["loss"].shape == (1024 // 64,)  # per-step metrics stacked
    assert int(state.step) == 4 * (1024 // 64)
    assert float(jnp.mean(metrics["accuracy"])) > 0.7


def test_eval_fn_matches_manual():
    data, model, tx, state = _tiny_setup(n=64)
    # eval batch 50 deliberately doesn't divide 128 -> exercises pad+mask
    eval_fn = jax.jit(make_eval_fn(model, batch_size=50))
    imgs = jnp.asarray(data["test_images"])
    labs = jnp.asarray(data["test_labels"])
    out = eval_fn(state, imgs, labs)
    logits = model.apply(
        {"params": state.params}, imgs.astype(jnp.float32) / 255.0, train=False
    )
    manual_acc = float(jnp.mean(logits.argmax(-1) == labs))
    assert abs(float(out["accuracy"]) - manual_acc) < 1e-5
    manual_loss = float(
        optax.softmax_cross_entropy_with_integer_labels(logits, labs).mean()
    )
    assert abs(float(out["loss"]) - manual_loss) < 1e-4


def test_batch_stats_model_trains():
    """ResNet-20 (BatchNorm) threads batch_stats through the compiled step."""
    data, model, tx, state = _tiny_setup("resnet20", n=64)
    assert jax.tree.leaves(state.batch_stats)
    step = jax.jit(make_train_step(model, tx))
    batch = {
        "image": jnp.asarray(data["train_images"][:32]),
        "label": jnp.asarray(data["train_labels"][:32]),
    }
    old_stats = jax.tree.leaves(state.batch_stats)
    state, metrics = step(state, batch)
    new_stats = jax.tree.leaves(state.batch_stats)
    assert any(not np.allclose(o, n) for o, n in zip(old_stats, new_stats))
    assert np.isfinite(float(metrics["loss"]))

"""Model zoo: output shapes, dtypes, gradient flow (SURVEY.md §4 unit tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import available_models, get_model



@pytest.mark.parametrize(
    "name,kwargs,in_shape",
    [
        ("mlp", {"hidden": (64,)}, (4, 28, 28, 1)),
        ("lenet5", {}, (4, 28, 28, 1)),
        ("resnet20", {}, (4, 28, 28, 1)),
        ("resnet50", {}, (2, 32, 32, 3)),
    ],
)
def test_forward_shapes(name, kwargs, in_shape):
    model = get_model(name, num_classes=10, **kwargs)
    x = jnp.zeros(in_shape, jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (in_shape[0], 10)
    assert logits.dtype == jnp.float32


def test_registry():
    assert set(available_models()) == {"mlp", "lenet5", "resnet20", "resnet50", "vit", "causal_lm"}
    with pytest.raises(ValueError):
        get_model("nope")


def test_lenet_dropout_needs_rng_only_in_train():
    model = get_model("lenet5", num_classes=10)
    x = jnp.ones((2, 28, 28, 1))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    # train=True with different dropout rngs gives different outputs
    a = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    b = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(a, b)
    # eval path is deterministic
    c = model.apply(variables, x, train=False)
    d = model.apply(variables, x, train=False)
    np.testing.assert_array_equal(np.asarray(c), np.asarray(d))


def test_resnet_batch_stats_update():
    model = get_model("resnet20", num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 28, 28, 1))
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, train=False)
    assert "batch_stats" in variables
    _, updated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(updated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_gradients_finite():
    model = get_model("lenet5", num_classes=10)
    x = jax.random.uniform(jax.random.PRNGKey(0), (4, 28, 28, 1))
    y = jnp.array([0, 1, 2, 3])
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, train=False)

    def loss(params):
        logits = model.apply({"params": params}, x, train=False)
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    grads = jax.grad(loss)(variables["params"])
    for g in jax.tree.leaves(grads):
        assert np.all(np.isfinite(g))
    # at least one nonzero gradient leaf
    assert any(np.abs(g).sum() > 0 for g in jax.tree.leaves(grads))


def test_lenet_conv1_s2d_matches_direct():
    """The polyphase space-to-depth conv1 (round 5) is the SAME function
    as the direct 5x5 C_in=1 conv, from the SAME parameter layout —
    checkpoints interchange between the two forms."""
    import jax
    import numpy as np
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    direct = get_model("lenet5", num_classes=10, dtype=jnp.float32,
                       dropout_rate=0.0)
    poly = get_model("lenet5", num_classes=10, dtype=jnp.float32,
                     dropout_rate=0.0, conv1_s2d=True)
    x = jnp.asarray(
        np.random.default_rng(0).random((4, 28, 28, 1)), jnp.float32)
    params = direct.init(jax.random.PRNGKey(0), x, train=False)["params"]
    # identical param trees: the polyphase form declares conv1/kernel+bias
    p2 = poly.init(jax.random.PRNGKey(0), x, train=False)["params"]
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(p2)
    assert params["conv1"]["kernel"].shape == p2["conv1"]["kernel"].shape

    a = direct.apply({"params": params}, x, train=False)
    b = poly.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

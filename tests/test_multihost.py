"""Multi-process (DCN) bootstrap test — launch/tpu_vm.bootstrap.

The reference bootstrapped its cluster from ClusterSpec + role flags over
gRPC (SURVEY.md §2.2 "Cluster resolution"); here two REAL processes join
via ``jax.distributed.initialize`` (the coordinator triple), form a global
2-device mesh, and run a cross-process collective — the DCN analog of the
multi-host TPU-VM flow, runnable in CI with no TPU.
"""

import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

WORKER = r'''
import sys, os
os.environ.pop("JAX_PLATFORMS", None)
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_tensorflow_ibm_mnist_tpu.launch.tpu_vm import bootstrap
info = bootstrap(sys.argv[2], 2, int(sys.argv[1]))
assert info["process_count"] == 2, info
assert info["global_devices"] == 2, info
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(jax.devices(), ("data",))
# Each process contributes ITS OWN shard of the global array — the
# multi-host input path (device_put requires identical values everywhere).
local = np.full((2,), float(info["process_index"] + 1), np.float32)
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local, (4,)
)
total = float(jax.jit(jnp.sum)(x))  # needs the other process's shard
assert total == 6.0, total  # proc 0's [1,1] + proc 1's [2,2]
print("OK", info["process_index"], flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker_src: str) -> list[tuple[int, str]]:
    """Launch two coordinator-joined worker processes; return (rc, output)."""
    addr = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(i), addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=str(REPO),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def test_two_process_bootstrap_and_collective():
    for rc, out in _run_workers(WORKER):
        assert rc == 0, out[-2000:]
        assert "OK" in out, out[-2000:]


TRAIN_WORKER = r'''
import sys, os
os.environ.pop("JAX_PLATFORMS", None)
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_tensorflow_ibm_mnist_tpu.launch.tpu_vm import bootstrap
info = bootstrap(sys.argv[2], 2, int(sys.argv[1]))
from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
import jax.numpy as jnp
cfg = RunConfig(
    name="mh", model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
    dataset="mnist", synthetic=True, n_train=256, n_test=64,
    batch_size=32, epochs=2, lr=2e-3, dp=2, quiet=True,
)
summary = Trainer(cfg).fit()
assert summary["epochs_run"] == 2, summary
import math
assert math.isfinite(summary["best_test_accuracy"]), summary
print("TRAINOK", info["process_index"], round(summary["best_test_accuracy"], 6), flush=True)
'''


def test_two_process_dp_training():
    """A REAL 2-process data-parallel fit: global mesh spans both processes;
    each host feeds its own shard; eval metrics agree across processes."""
    accs = []
    for rc, out in _run_workers(TRAIN_WORKER):
        assert rc == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("TRAINOK")]
        assert line, out[-2000:]
        accs.append(line[0].split()[-1])
    assert accs[0] == accs[1], accs  # SPMD: both processes see identical metrics


FSDP_WORKER = r'''
import sys, os
os.environ.pop("JAX_PLATFORMS", None)
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_tensorflow_ibm_mnist_tpu.launch.tpu_vm import bootstrap
info = bootstrap(sys.argv[2], 2, int(sys.argv[1]))
from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
cfg = RunConfig(
    name="mh_fsdp", model="mlp", model_kwargs={"hidden": (128,), "dtype": jnp.float32},
    dataset="mnist", synthetic=True, n_train=256, n_test=64,
    batch_size=32, epochs=2, lr=2e-3, dp=2, fsdp=True, quiet=True, eval_batch_size=64,
)
t = Trainer(cfg)
k = t.state.params["dense_0"]["kernel"]
assert "data" in tuple(k.sharding.spec), k.sharding.spec  # ZeRO-3 across HOSTS
assert len(k.addressable_shards) == 1  # this process holds exactly its shard
summary = t.fit()
assert summary["epochs_run"] == 2, summary
import math
assert math.isfinite(summary["best_test_accuracy"]), summary
print("FSDPOK", info["process_index"], round(summary["best_test_accuracy"], 6), flush=True)
'''


def test_two_process_fsdp_training():
    """GSPMD across REAL processes: a 2-process ZeRO-3 fit where each host
    owns 1/2 of every large parameter (and of the test set — the sharded
    eval path's multi-process make_array_from_callback placement), with
    identical metrics on both processes."""
    accs = []
    for rc, out in _run_workers(FSDP_WORKER):
        assert rc == 0, out[-2000:]
        line = [l for l in out.splitlines() if l.startswith("FSDPOK")]
        assert line, out[-2000:]
        accs.append(line[0].split()[-1])
    assert accs[0] == accs[1], accs

"""Synthetic dataset generator: shapes, determinism, learnability surface."""

import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.data import (
    load_dataset,
    synthetic_cifar10,
    synthetic_fashion_mnist,
    synthetic_mnist,
)


pytestmark = pytest.mark.quick  # core numerics: part of the -m quick signal loop


def test_mnist_shapes_and_dtypes():
    d = synthetic_mnist(n_train=512, n_test=128, seed=0)
    assert d["train_images"].shape == (512, 28, 28, 1)
    assert d["train_images"].dtype == np.uint8
    assert d["train_labels"].shape == (512,)
    assert d["train_labels"].dtype == np.int32
    assert d["test_images"].shape == (128, 28, 28, 1)
    assert d["num_classes"] == 10
    assert 0 <= d["train_labels"].min() and d["train_labels"].max() <= 9


def test_mnist_deterministic():
    a = synthetic_mnist(n_train=64, n_test=16, seed=3)
    b = synthetic_mnist(n_train=64, n_test=16, seed=3)
    np.testing.assert_array_equal(a["train_images"], b["train_images"])
    np.testing.assert_array_equal(a["train_labels"], b["train_labels"])
    c = synthetic_mnist(n_train=64, n_test=16, seed=4)
    assert not np.array_equal(a["train_images"], c["train_images"])


def test_train_test_disjoint_streams():
    d = synthetic_mnist(n_train=64, n_test=64, seed=0)
    assert not np.array_equal(d["train_images"][:64], d["test_images"][:64])


def test_classes_visually_distinct():
    """Mean image per class should differ between classes (else unlearnable)."""
    d = synthetic_mnist(n_train=2000, n_test=10, seed=0)
    x = d["train_images"].astype(np.float32) / 255.0
    y = d["train_labels"]
    means = np.stack([x[y == c].mean(axis=0) for c in range(10)])
    dists = np.linalg.norm(
        (means[:, None] - means[None, :]).reshape(10, 10, -1), axis=-1
    )
    off_diag = dists[~np.eye(10, dtype=bool)]
    assert off_diag.min() > 1.0, "some class templates are nearly identical"


def test_labels_roughly_balanced():
    d = synthetic_mnist(n_train=5000, n_test=10, seed=0)
    counts = np.bincount(d["train_labels"], minlength=10)
    assert counts.min() > 300


@pytest.mark.parametrize(
    "fn,shape",
    [
        (synthetic_fashion_mnist, (28, 28, 1)),
        (synthetic_cifar10, (32, 32, 3)),
    ],
)
def test_other_datasets(fn, shape):
    d = fn(n_train=128, n_test=32, seed=0)
    assert d["train_images"].shape == (128,) + shape
    assert d["num_classes"] == 10


def test_load_dataset_fallback_to_synthetic():
    d = load_dataset("mnist", n_train=64, n_test=16, seed=0)
    assert d["train_images"].shape == (64, 28, 28, 1)


def test_load_dataset_unknown_raises():
    with pytest.raises(ValueError):
        load_dataset("imagenet")

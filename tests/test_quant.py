"""Weight-only int8 decode compute (ISSUE 12): quantization is invisible
in the tokens and ~4x smaller in the weight stream.

The decisive properties:

* STRUCTURE — ``quantize_params_int8`` rewrites every block projection
  and the untied logits head to int8 kernels + per-output-channel f32
  scales, leaves embeddings/norms/biases untouched, and is IDEMPOTENT
  (the engine calls it unconditionally at upload and swap).
* NUMERICS — ``Int8Dense`` computes exactly ``(x @ q) * scale + bias``
  with f32 accumulation; the end-to-end quant model's logits drift from
  full precision by a bounded amount, and greedy serving agrees with
  the full-precision engine above the pinned floor.
* COMPOSITION — paged/dense, decode_ahead 1/8 and speculative/plain are
  token-identical UNDER quant (the engine's program family is
  quant-blind); ``swap_params`` re-quantizes a full-precision host
  tree; ``prewarm()`` covers the quant family so serving compiles zero
  programs.
* SATELLITE 1 — with int8 KV quant on, attention probabilities stay f32
  into the PV einsum even on a bf16 model (models/transformer.py
  ``_attend_cached``); the teacher-forcing drift bound pins it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.models.quant import (
    Int8Dense,
    is_quantized,
    quantize_kernel_int8,
    quantize_params_int8,
    weight_stream_bytes,
)
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
)

KW = dict(num_classes=16, dim=64, depth=2, heads=4, dtype=jnp.float32)

MAX_LEN = 32
# repetitive suffixes so the speculative case's n-gram drafter gets hits
PROMPTS = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 5, 4, 5, 4, 5], [6, 7, 8, 9],
           [2, 4, 2, 4, 2, 4]]


def _model_and_params(seed=0, **over):
    model = get_model("causal_lm", **{**KW, **over})
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, **ekw):
    return InferenceEngine(
        model, params, slots=2, max_len=MAX_LEN,
        scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(16,),
                                max_queue=len(PROMPTS)),
        **ekw)


def _serve(model, params, max_new=6, prompts=PROMPTS, **ekw):
    eng = _engine(model, params, **ekw)
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.run()
    outs = [list(r.generated) for r in reqs]
    eng.close()
    return outs


@pytest.fixture(scope="module")
def fp():
    return _model_and_params()


# ----------------------------------------------------------------------
# structure: what quantizes, what doesn't, and idempotence


def test_quantize_structure(fp):
    _, params = fp
    q = quantize_params_int8(params)
    blk = q["block_0"]
    # every projection: int8 kernel + per-output-channel f32 scale
    for name, dim_out in (("qkv", 3 * KW["dim"]), ("proj", KW["dim"]),
                          ("dense_0", 4 * KW["dim"]),
                          ("dense_1", KW["dim"])):
        assert blk[name]["kernel"].dtype == jnp.int8, name
        assert blk[name]["scale"].shape == (dim_out,), name
        assert blk[name]["scale"].dtype == jnp.float32, name
        assert blk[name]["bias"].dtype == params["block_0"][name]["bias"].dtype
    assert q["logits"]["kernel"].dtype == jnp.int8
    # NOT quantized: embedding (a gather), norms (1-D "scale"/"bias")
    assert q["embed"]["embedding"].dtype == jnp.float32
    assert q["block_0"]["norm_attn"]["scale"].dtype == jnp.float32
    assert is_quantized(q) and not is_quantized(params)


def test_quantize_idempotent(fp):
    _, params = fp
    q1 = quantize_params_int8(params)
    q2 = quantize_params_int8(q1)
    flat1 = jax.tree_util.tree_leaves_with_path(q1)
    flat2 = jax.tree_util.tree_leaves_with_path(q2)
    assert [p for p, _ in flat1] == [p for p, _ in flat2]
    for (_, a), (_, b) in zip(flat1, flat2):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quantize_kernel_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 48), jnp.float32)
    q, scale = quantize_kernel_int8(w)
    assert q.dtype == jnp.int8 and scale.shape == (48,)
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) <= 127
    # symmetric per-column: reconstruction error <= scale/2 elementwise
    err = jnp.abs(q.astype(jnp.float32) * scale - w)
    assert bool(jnp.all(err <= 0.5 * scale + 1e-7))


def test_int8_dense_matches_manual_dequant():
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (64, 16), jnp.float32)
    bias = jax.random.normal(jax.random.PRNGKey(4), (16,), jnp.float32)
    q, scale = quantize_kernel_int8(w)
    layer = Int8Dense(16, dtype=jnp.float32)
    got = layer.apply(
        {"params": {"kernel": q, "scale": scale, "bias": bias}}, x)
    want = (x @ (q.astype(jnp.float32))) * scale + bias
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_weight_stream_bytes_ratio(fp):
    _, params = fp
    q = quantize_params_int8(params)
    ratio = weight_stream_bytes(params) / weight_stream_bytes(q)
    # kernels go 4 -> 1 byte (+scales); embed/norms/biases stay f32, so
    # the whole-tree ratio lands under 4x but well above 3x at this size
    assert 3.2 <= ratio <= 4.0, ratio


# ----------------------------------------------------------------------
# numerics: drift bound and greedy agreement


def test_quant_forward_logit_drift_bounded(fp):
    model, params = fp
    qmodel = model.clone(quant="int8")
    qparams = quantize_params_int8(params)
    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, 16, size=(2, 16)), jnp.int32)
    ref = model.apply({"params": params}, tokens)
    got = qmodel.apply({"params": qparams}, tokens)
    drift = float(jnp.max(jnp.abs(ref - got)))
    # measured 0.041 at this size/seed vs max |logit| 3.6; 0.15 is the
    # regression ceiling, not the expectation
    assert drift < 0.15, drift


def test_engine_greedy_agreement_and_bytes(fp):
    model, params = fp
    ref = _serve(model, params)
    eng = _engine(model, params, quant="int8")
    assert is_quantized(eng_params_host(eng))
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    got = [list(r.generated) for r in reqs]
    qbytes = eng.weight_bytes_per_chip()
    assert eng.stats.summary()["quant"] == "int8"
    eng.close()
    total = sum(len(t) for t in ref)
    agree = sum(a == b for rt, gt in zip(ref, got)
                for a, b in zip(rt, gt))
    assert agree / total >= 0.9, (agree, total)  # measured 24/24

    feng = _engine(model, params)
    fbytes = feng.weight_bytes_per_chip()
    assert feng.stats.summary()["quant"] == "none"
    feng.close()
    assert 3.2 <= fbytes / qbytes <= 4.0, (fbytes, qbytes)


def eng_params_host(eng):
    return jax.tree.map(np.asarray, jax.device_get(eng.params))


# ----------------------------------------------------------------------
# composition: layout/window/spec invariance, swap, prewarm


def test_quant_layout_invariance(fp):
    """dense == paged == decode_ahead 8 == speculative, all WITH quant:
    the program family is quant-blind, so every serving layout reads the
    same int8 tree and says the same tokens."""
    model, params = fp
    base = _serve(model, params, quant="int8")
    assert _serve(model, params, quant="int8", kv_page_size=8) == base
    assert _serve(model, params, quant="int8", decode_ahead=8) == base
    assert _serve(model, params, quant="int8", speculative="ngram",
                  draft_len=3) == base


def test_swap_params_requantizes(fp):
    """swap_params with a full-precision HOST tree: the engine quantizes
    at the seam, and serves token-identically to a fresh quant engine
    built on those weights."""
    model, params = fp
    model2, params2 = _model_and_params(seed=3)
    want2 = _serve(model2, params2, quant="int8")

    eng = _engine(model, params, quant="int8")
    host_tree = jax.tree.map(np.asarray, jax.device_get(params2))
    eng.swap_params(host_tree)
    assert eng.params["block_0"]["qkv"]["kernel"].dtype == jnp.int8
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    assert [list(r.generated) for r in reqs] == want2
    eng.close()


def test_quant_prewarm_zero_serving_compiles(fp):
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        CompileTracker,
    )

    model, params = fp
    tracker = CompileTracker.install()
    eng = _engine(model, params, quant="int8")
    eng.prewarm()
    before = tracker.snapshot()
    reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
    eng.run()
    d = CompileTracker.delta(tracker.snapshot(), before)
    assert d["n_compiled_programs"] == 0, d["by_site"]
    assert all(r.status == "done" for r in reqs)
    eng.close()


# ----------------------------------------------------------------------
# rejections


def test_engine_rejects_unknown_quant(fp):
    model, params = fp
    with pytest.raises(ValueError, match="quant"):
        _engine(model, params, quant="int4")


def test_model_rejects_quant_with_pp_stages():
    model = get_model("causal_lm", **KW, quant="int8", pp_stages=2)
    with pytest.raises(ValueError, match="pp_stages"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


def test_model_rejects_unknown_quant_value():
    model = get_model("causal_lm", **KW, quant="fp4")
    with pytest.raises(ValueError, match="quant"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


# ----------------------------------------------------------------------
# satellite 1: int8 KV on a bf16 model keeps the PV einsum's attention
# probabilities in f32 (models/transformer._attend_cached p_dtype)


def test_int8_kv_bf16_pv_probs_stay_f32_drift_bounded():
    """Teacher-forcing decode on a BF16 model with kv_cache_dtype='int8'
    vs the same model on the native cache: the f32-probability PV path
    keeps the drift at the int8-quantization level (measured 0.032);
    without it, bf16 probs stack a second rounding on top."""
    model, params = _model_and_params(seed=14, dtype=jnp.bfloat16)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, 16, size=(2, 16)), jnp.int32)

    def run(kv):
        m = model.clone(kv_cache_dtype=kv)
        logits, vars_ = m.apply({"params": params}, tokens[:, :8],
                                decode=True, max_len=16, mutable=["cache"])
        cache = vars_["cache"]
        out = [logits]
        for t in range(8, 16):
            sl, vars_ = m.apply({"params": params, "cache": cache},
                                tokens[:, t:t + 1], decode=True,
                                max_len=16, mutable=["cache"])
            cache = vars_["cache"]
            out.append(sl)
        return jnp.concatenate(out, axis=1)

    drift = float(jnp.max(jnp.abs(run("native") - run("int8"))))
    assert drift < 0.05, drift

"""End-to-end Trainer smoke tests — BASELINE.md config 1 shape (SURVEY.md §4)."""

import jax.numpy as jnp

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import PRESETS, RunConfig, get_preset


def test_presets_cover_baseline_configs():
    assert set(PRESETS) == {
        "mnist_mlp_smoke",
        "mnist_lenet_1chip",
        "mnist_cnn_dp8",
        "fashion_resnet20_dp32",
        "cifar_resnet50_dp32",
    }
    assert get_preset("mnist_mlp_smoke").model == "mlp"


def test_mlp_smoke_end_to_end():
    """Config 1 (MNIST MLP, batch 32) shrunk for CI: learns well above chance."""
    cfg = RunConfig(
        name="smoke", model="mlp", model_kwargs={"hidden": (128,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=2048, n_test=512,
        batch_size=32, epochs=3, lr=2e-3, dp=1, eval_every=3, quiet=True,
    )
    trainer = Trainer(cfg)
    summary = trainer.fit()
    assert summary["best_test_accuracy"] > 0.85
    assert summary["images_per_sec"] > 0
    assert summary["epochs_run"] == 3
    assert trainer.history[-1]["test_accuracy"] > 0.85


def test_trainer_dp8_end_to_end(eight_devices):
    """Config 3 shape (DP over 8 devices) shrunk for CI."""
    cfg = RunConfig(
        name="dp8_smoke", model="mlp", model_kwargs={"hidden": (128,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=2048, n_test=512,
        batch_size=256, epochs=4, lr=4e-3, dp=8, eval_every=4, quiet=True,
    )
    trainer = Trainer(cfg)
    summary = trainer.fit()
    assert summary["best_test_accuracy"] > 0.8


def test_trainer_early_stop_on_target():
    cfg = RunConfig(
        name="early", model="mlp", model_kwargs={"hidden": (128,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=2048, n_test=256,
        batch_size=64, epochs=20, lr=2e-3, dp=1,
        target_accuracy=0.5, eval_every=1, quiet=True,
    )
    summary = Trainer(cfg).fit()
    assert summary["epochs_run"] < 20
    assert summary["time_to_target_s"] is not None


def test_batch_larger_than_dataset_raises():
    import pytest

    cfg = RunConfig(
        model="mlp", synthetic=True, n_train=64, n_test=16, batch_size=128, quiet=True,
    )
    with pytest.raises(ValueError, match="exceeds training-set size"):
        Trainer(cfg)


def test_dp_resnet_gets_cross_replica_bn(eight_devices):
    cfg = RunConfig(
        model="resnet20", synthetic=True, n_train=128, n_test=64,
        batch_size=64, epochs=1, dp=8, quiet=True, eval_batch_size=64,
    )
    t = Trainer(cfg)
    assert t.model.axis_name == "data"
    t.fit()  # runs: BN pmean works inside shard_map

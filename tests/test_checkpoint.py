"""Checkpoint/resume round-trip (SURVEY.md §5 'Checkpoint / resume')."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, Trainer
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_state,
    save_state,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig


def _state(seed=0):
    model = get_model("mlp", num_classes=10, hidden=(32,))
    tx = optax.adam(1e-3)
    return model, tx, TrainState.create(
        model, tx, jax.random.PRNGKey(seed), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )


def test_state_roundtrip(tmp_path):
    _, _, state = _state(seed=1)
    state = state.replace(step=jnp.asarray(42, jnp.int32))
    save_state(str(tmp_path / "ckpt"), state)
    _, _, fresh = _state(seed=2)  # different init -> must be overwritten
    restored = restore_state(str(tmp_path / "ckpt"), fresh)
    assert int(restored.step) == 42
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_manager_latest_and_missing(tmp_path):
    _, _, state = _state()
    mgr = CheckpointManager(str(tmp_path / "c"))
    assert mgr.latest_step() is None
    with pytest.raises(FileNotFoundError):
        mgr.restore(state)
    mgr.save(state, wait=True)
    state2 = state.replace(step=jnp.asarray(7, jnp.int32))
    mgr.save(state2, wait=True)
    assert mgr.latest_step() == 7
    mgr.close()


def test_trainer_resume_continues_training(tmp_path):
    """Train 2 epochs, checkpoint, resume in a NEW trainer, keep training."""
    cfg = RunConfig(
        name="ckpt_run", model="mlp", model_kwargs={"hidden": (64,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=2, lr=2e-3, dp=1, quiet=True,
        checkpoint_dir=str(tmp_path / "run_ckpt"),
    )
    t1 = Trainer(cfg)
    t1.fit()
    saved_step = int(jax.device_get(t1.state.step))
    assert saved_step == 2 * t1.steps_per_epoch

    t2 = Trainer(cfg)
    restored_step = t2.restore_checkpoint()
    assert restored_step == saved_step
    for a, b in zip(jax.tree.leaves(t1.state.params), jax.tree.leaves(t2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # resumed trainer can keep training
    t2.fit()
    assert int(jax.device_get(t2.state.step)) > saved_step


def test_save_at_existing_step_overwrites(tmp_path):
    """Re-saving at the same step must not silently keep the old weights."""
    model, tx, state = _state(seed=1)
    mgr = CheckpointManager(str(tmp_path / "ow"))
    mgr.save(state, wait=True)
    bumped = jax.tree.map(lambda p: p + 1.0, state.params)
    state2 = state.replace(params=bumped)  # same step, different weights
    mgr.save(state2, wait=True)
    restored = mgr.restore(state)
    for a, b in zip(jax.tree.leaves(bumped), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_trainer_config_resume_flag(tmp_path):
    cfg = RunConfig(
        name="r", model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=32, epochs=1, dp=1, quiet=True,
        checkpoint_dir=str(tmp_path / "rck"),
    )
    t1 = Trainer(cfg)
    t1.fit()
    first_step = int(jax.device_get(t1.state.step))
    t2 = Trainer(cfg.replace(resume=True))
    t2.fit()
    assert int(jax.device_get(t2.state.step)) == 2 * first_step


def test_checkpoint_cadence_independent_of_eval_every(tmp_path):
    """checkpoint_every must be honored even between eval boundaries.

    Metric readbacks are deferred to eval boundaries (Trainer.fit keeps the
    device queue full between them), but a configured checkpoint cadence is
    its own sync point — eval_every=100 with checkpoint_every=1 still saves
    after every epoch.
    """
    cfg = RunConfig(
        name="cad", model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=32, epochs=3, dp=1, quiet=True,
        checkpoint_dir=str(tmp_path / "cad"), checkpoint_every=1, eval_every=100,
    )
    t = Trainer(cfg)

    seen = []
    orig = Trainer.save_checkpoint

    def spy(self, wait=True):
        seen.append(int(jax.device_get(self.state.step)))
        return orig(self, wait=wait)

    Trainer.save_checkpoint = spy
    try:
        t.fit()
    finally:
        Trainer.save_checkpoint = orig
    spe = t.steps_per_epoch
    # one save per epoch cadence + the final save at exit
    assert seen[:3] == [spe, 2 * spe, 3 * spe], seen


def test_resume_metric_records_continue_step_axis(tmp_path):
    """After resume, epoch records must not rewind the step axis to 0."""
    cfg = RunConfig(
        name="stepaxis", model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=32, epochs=2, dp=1, quiet=True,
        checkpoint_dir=str(tmp_path / "sx"),
    )
    t1 = Trainer(cfg)
    t1.fit()
    first_steps = 2 * t1.steps_per_epoch

    t2 = Trainer(cfg.replace(resume=True))
    records = []
    t2.writer.write = lambda kind, **kw: records.append((kind, kw))
    t2.fit()
    epoch_steps = [kw["step"] for kind, kw in records if kind == "epoch"]
    assert epoch_steps[0] == first_steps + t2.steps_per_epoch, epoch_steps


def test_checkpoint_roundtrip_across_process_counts(tmp_path, eight_devices):
    """SURVEY.md §5: a checkpoint must round-trip across device layouts.

    Save from an 8-way DP (replicated) trainer, restore into a single-device
    trainer — and back the other way — with identical params and a working
    continued-training step in the new layout.
    """
    base = RunConfig(
        name="xproc", model="mlp", model_kwargs={"hidden": (64,), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=1, lr=2e-3, quiet=True,
        checkpoint_dir=str(tmp_path / "xp"),
    )
    t8 = Trainer(base.replace(dp=8))
    t8.fit()  # saves at exit
    step8 = int(jax.device_get(t8.state.step))

    # 8-way -> 1-way
    t1 = Trainer(base.replace(dp=1))
    assert t1.restore_checkpoint() == step8
    for a, b in zip(jax.tree.leaves(t8.state.params), jax.tree.leaves(t1.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0, atol=0)
    t1.fit()
    assert int(jax.device_get(t1.state.step)) == step8 + t1.steps_per_epoch

    # 1-way -> 8-way
    t8b = Trainer(base.replace(dp=8))
    assert t8b.restore_checkpoint() == int(jax.device_get(t1.state.step))
    for a, b in zip(jax.tree.leaves(t1.state.params), jax.tree.leaves(t8b.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0, atol=0)
    t8b.fit()
    assert int(jax.device_get(t8b.state.step)) > step8 + t8b.steps_per_epoch


def test_sharded_save_no_host_gather(tmp_path, eight_devices):
    """FSDP checkpointing never gathers the full state to host: save hands
    orbax the sharded jax.Arrays as placed (VERDICT.md round-1 item 4), and
    restore lands leaves directly in the target's sharded layout."""
    import pytest

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils import checkpoint as ckpt_mod
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="fsdp_ck", model="mlp", model_kwargs={"hidden": (256,)},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=64, epochs=1, dp=8, fsdp=True, quiet=True,
        checkpoint_dir=str(tmp_path / "ck"), eval_batch_size=64,
    )
    t = Trainer(cfg)
    t.fit()
    fsdp_spec = t.state.params["dense_0"]["kernel"].sharding.spec
    assert "data" in tuple(fsdp_spec)

    class _NoDeviceGet:
        """jax proxy that forbids full-tree host gathers inside the manager
        (scalar step readback excepted via the real jax on other attrs)."""

        def __getattr__(self, name):
            if name == "device_get":
                return self._guarded
            return getattr(jax, name)

        @staticmethod
        def _guarded(x):
            if hasattr(x, "ndim") and getattr(x, "ndim", 1) == 0:
                return jax.device_get(x)  # scalar step counter only
            raise AssertionError("full-state host gather in checkpoint path")

    real_jax = ckpt_mod.jax
    ckpt_mod.jax = _NoDeviceGet()
    try:
        step = t._ckpt.save(t.state, wait=True)
        restored = t._ckpt.restore(t.state, step=step)
    finally:
        ckpt_mod.jax = real_jax

    # restored leaves arrive already in the FSDP layout
    assert restored.params["dense_0"]["kernel"].sharding.spec == fsdp_spec
    import numpy as np

    for a, b in zip(jax.tree.leaves(jax.device_get(t.state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_distinct_step_saves_do_not_block(tmp_path, monkeypatch):
    """Saving a NEW step must not wait on an in-flight async save (round-1
    weak item 3: the old pre-save wait serialized the async pipeline)."""
    import jax.numpy as jnp
    import optax

    from distributed_tensorflow_ibm_mnist_tpu.core.state import TrainState
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.utils.checkpoint import CheckpointManager

    model = get_model("mlp", num_classes=10, hidden=(16,))
    tx = optax.sgd(1e-2)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    mgr = CheckpointManager(str(tmp_path / "ck"))
    # Stub the orbax layer: this asserts OUR wrapper's control flow (orbax's
    # save() has its own internal one-in-flight serialization on top).
    calls = []
    monkeypatch.setattr(mgr._mgr, "wait_until_finished", lambda: calls.append("wait"))
    monkeypatch.setattr(mgr._mgr, "save", lambda *a, **k: calls.append("save"))
    monkeypatch.setattr(mgr._mgr, "delete", lambda s: calls.append("delete"))

    monkeypatch.setattr(mgr._mgr, "all_steps", lambda: [])
    mgr.save(state, wait=False)
    assert calls == ["save"], "a fresh step must not wait on in-flight saves"

    calls.clear()
    monkeypatch.setattr(mgr._mgr, "all_steps", lambda: [0])
    mgr.save(state, wait=False)  # same-step overwrite: wait THEN delete
    assert calls == ["wait", "delete", "save"]


def test_restore_then_generate_uses_restored_weights(tmp_path):
    """Checkpoint -> fresh Trainer -> restore -> generate: the decode-params
    cache is invalidated by the restore (r4), so generation reflects the
    RESTORED weights, matching the original trainer's decode exactly."""
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="ckgen", model="causal_lm",
        model_kwargs={"dim": 32, "depth": 1, "heads": 2, "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 32},
        n_train=128, n_test=32, batch_size=64, epochs=1, quiet=True,
        eval_batch_size=32, checkpoint_dir=str(tmp_path / "ck"),
    )
    t = Trainer(cfg)
    t.fit()
    t.save_checkpoint(wait=True)
    prompt = jnp.asarray([[2, 9, 4, 7]], jnp.int32)
    want = np.asarray(t.generate(prompt, max_new=8))

    t2 = Trainer(cfg)
    before = np.asarray(t2.generate(prompt, max_new=8))  # fresh-init decode
    t2.restore_checkpoint()
    got = np.asarray(t2.generate(prompt, max_new=8))
    np.testing.assert_array_equal(got, want)
    # and the restore really invalidated the cached fresh-init params
    # (otherwise got would equal the fresh-init decode whenever they differ)
    if not np.array_equal(before, want):
        assert not np.array_equal(got, before)


def test_intact_restore_observer_races_concurrent_writer(tmp_path):
    """Satellite (ISSUE 8): a second CheckpointManager OBSERVING a
    directory another manager writes — the WeightWatcher pattern.  The
    observer's listing is refreshed by reload() (orbax caches it per
    manager, correct for the writer, stale for a watcher); a newest step
    whose bytes are torn restores as the PREVIOUS intact step; and the
    intact-walk waits only on the observer's OWN in-flight saves (none),
    so polling returns while the writer's async save is still landing —
    it can never block the save pipeline."""
    import os

    writer = CheckpointManager(str(tmp_path / "ck"))
    observer = CheckpointManager(str(tmp_path / "ck"))
    _, _, good = _state(seed=1)
    writer.save(good.replace(step=jnp.asarray(5, jnp.int32)), wait=True)

    observer.reload()  # without this the cached listing still says "empty"
    assert observer.latest_step() == 5
    assert int(observer.restore_latest_intact(_state(seed=3)[2]).step) == 5

    # the race window: the writer's NEWEST step is on disk but torn
    # (crash mid-write / bytes landed ahead of the manifest) — the
    # observer must skip it and land on the previous intact step
    writer.save(good.replace(step=jnp.asarray(10, jnp.int32)), wait=True)
    victim, vsize = None, -1
    for dirpath, _d, files in os.walk(tmp_path / "ck" / "10"):
        for name in files:
            p = os.path.join(dirpath, name)
            if os.path.getsize(p) > vsize:
                victim, vsize = p, os.path.getsize(p)
    with open(victim, "r+b") as f:
        f.truncate(vsize // 2)
    observer.reload()
    assert observer.latest_step() == 10
    restored = observer.restore_latest_intact(_state(seed=3)[2])
    assert int(restored.step) == 5
    for a, b in zip(jax.tree.leaves(good.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # an ASYNC save in flight on the writer: the observer's walk returns
    # (torn 10 still skipped, never a hang joining the writer's save) and
    # the writer's save completes cleanly afterwards
    writer.save(good.replace(step=jnp.asarray(15, jnp.int32)), wait=False)
    observer.reload()
    got = observer.restore_latest_intact(_state(seed=3)[2])
    assert int(got.step) in (5, 15)  # whichever side of the landing — never 10
    writer.wait()
    observer.reload()
    assert int(observer.restore_latest_intact(_state(seed=3)[2]).step) == 15
    writer.close()
    observer.close()

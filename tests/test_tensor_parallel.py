"""Tensor parallelism (GSPMD) on the 8-device virtual CPU mesh.

The acceptance criterion mirrors data_parallel's: a dp=2 x tp=4 sharded run
of the UNCHANGED train step is numerically the single-device run, and the
params/opt-state really are sharded over the ``model`` axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    make_param_specs,
    make_tp_train_step,
    megatron_dense_rule,
    megatron_rule,
    shard_train_state,
    specs_like,
)


def _mlp_state(hidden=(64, 64)):
    model = get_model("mlp", num_classes=10, hidden=hidden, dtype=jnp.float32)
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    return model, tx, state


def _batches(n_steps=3, batch=64, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        out.append({
            "image": jnp.asarray(rng.integers(0, 255, size=(batch, 28, 28, 1), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(batch,)).astype(np.int32)),
        })
    return out


def test_megatron_rule_specs():
    _, _, state = _mlp_state(hidden=(64, 32))
    specs = make_param_specs(state.params, megatron_dense_rule())
    assert specs["dense_0"]["kernel"] == P(None, "model")
    assert specs["dense_0"]["bias"] == P("model")
    assert specs["dense_1"]["kernel"] == P("model", None)
    assert specs["dense_1"]["bias"] == P()
    assert specs["logits"]["kernel"] == P()


def test_megatron_full_rule_vit_specs():
    """qkv column-parallel, proj row-parallel, patch-embed conv out-sharded,
    logits row-parallel — the whole ViT's FLOPs run tp-wide, not just MLPs."""
    model = get_model("vit", num_classes=10, patch_size=7, dim=32, depth=1, heads=2)
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    specs = make_param_specs(state.params, megatron_rule(4))
    blk = specs["block_0"]
    assert blk["qkv"]["kernel"] == P(None, "model")
    assert blk["qkv"]["bias"] == P("model")
    assert blk["proj"]["kernel"] == P("model", None)
    assert blk["proj"]["bias"] == P()
    assert blk["dense_0"]["kernel"] == P(None, "model")
    assert blk["dense_1"]["kernel"] == P("model", None)
    assert specs["patch_embed"]["kernel"] == P(None, None, None, "model")
    assert specs["logits"]["kernel"] == P("model", None)
    assert specs["pos_embed"] == P()
    assert specs["norm_out"]["scale"] == P()


def test_megatron_full_rule_conv_and_divisibility():
    """LeNet: convs out-channel-sharded, fc1 column / logits row; leaves whose
    dims don't divide the shard count degrade to replicated, never fail."""
    model = get_model("lenet5", num_classes=10)
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    specs = make_param_specs(state.params, megatron_rule(4))
    assert specs["conv1"]["kernel"] == P(None, None, None, "model")
    assert specs["conv2"]["kernel"] == P(None, None, None, "model")
    assert specs["fc1"]["kernel"] == P(None, "model")
    assert specs["fc1"]["bias"] == P("model")
    assert specs["logits"]["kernel"] == P("model", None)
    assert specs["logits"]["bias"] == P()
    # 7 shards divide nothing in LeNet's conv1 (32 channels) -> replicated
    specs7 = make_param_specs(state.params, megatron_rule(7))
    assert specs7["conv1"]["kernel"] == P()
    assert specs7["fc1"]["kernel"] == P()


def test_full_rule_vit_matches_single_device(eight_devices):
    """tp=4 ViT with the FULL megatron rule (attention + patch conv + head
    sharded) reproduces single-device numerics."""
    model = get_model(
        "vit", num_classes=10, patch_size=7, dim=32, depth=2, heads=2,
        dtype=jnp.float32,
    )
    tx = optax.adam(1e-3)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(1), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    specs = make_param_specs(state.params, megatron_rule(4))
    batches = _batches(n_steps=2, batch=16, seed=1)

    ref_step = jax.jit(make_train_step(model, tx))
    ref_state = state
    for b in batches:
        ref_state, ref_metrics = ref_step(ref_state, b)

    mesh = make_mesh(dp=2, tp=4)
    tp_state = shard_train_state(mesh, state, specs)
    tp_step = make_tp_train_step(model, tx, mesh, specs, state)
    for b in batches:
        tp_state, tp_metrics = tp_step(tp_state, b)

    # the attention projections are REALLY sharded (VERDICT.md round-1 item 2)
    assert tp_state.params["block_0"]["qkv"]["kernel"].sharding.spec == P(None, "model")
    assert tp_state.params["block_0"]["proj"]["kernel"].sharding.spec == P("model", None)
    assert tp_state.params["patch_embed"]["kernel"].sharding.spec == P(None, None, None, "model")
    assert tp_state.params["logits"]["kernel"].sharding.spec == P("model", None)

    np.testing.assert_allclose(
        float(tp_metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    # atol admits float32 reduction-order drift: the sharded qkv/proj matmuls
    # accumulate partial sums in a different order, and adam's rsqrt amplifies
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(tp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_full_rule_resnet_matches_single_device(eight_devices):
    """tp=4 ResNet-20 (conv channels sharded, BN stats replicated) matches the
    single-device step — conv TP is real, not vacuous (VERDICT.md item 2)."""
    model = get_model("resnet20", num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(1e-2)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(2), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    specs = make_param_specs(state.params, megatron_rule(4))
    (batch,) = _batches(n_steps=1, batch=8, seed=2)

    ref_step = jax.jit(make_train_step(model, tx))
    ref_state, ref_metrics = ref_step(state, batch)

    mesh = make_mesh(dp=2, tp=4)
    tp_state = shard_train_state(mesh, state, specs)
    tp_step = make_tp_train_step(model, tx, mesh, specs, state)
    tp_state, tp_metrics = tp_step(tp_state, batch)

    assert tp_state.params["stem"]["kernel"].sharding.spec == P(None, None, None, "model")
    assert (
        tp_state.params["stage1_block0"]["conv1"]["kernel"].sharding.spec
        == P(None, None, None, "model")
    )
    np.testing.assert_allclose(
        float(tp_metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(tp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_specs_like_propagates_to_opt_state():
    _, tx, state = _mlp_state(hidden=(64, 32))
    specs = make_param_specs(state.params, megatron_dense_rule())
    st_specs = specs_like(state, state.params, specs)
    # adam mu mirrors the param tree -> same specs by path suffix
    mu_specs = st_specs.opt_state[0].mu
    assert mu_specs["dense_0"]["kernel"] == P(None, "model")
    assert mu_specs["dense_1"]["kernel"] == P("model", None)
    # scalar count and the step counter fall back to replicated
    assert st_specs.opt_state[0].count == P()
    assert st_specs.step == P()


def test_tp_matches_single_device(eight_devices):
    mesh = make_mesh(dp=2, tp=4)
    model, tx, state = _mlp_state(hidden=(64, 64))
    specs = make_param_specs(state.params, megatron_dense_rule())
    batches = _batches()

    # single-device reference
    ref_step = jax.jit(make_train_step(model, tx))
    ref_state = state
    for b in batches:
        ref_state, ref_metrics = ref_step(ref_state, b)

    # dp=2 x tp=4 sharded run of the same step
    tp_state = shard_train_state(mesh, state, specs)
    tp_step = make_tp_train_step(model, tx, mesh, specs, state)
    for b in batches:
        tp_state, tp_metrics = tp_step(tp_state, b)

    # params really sharded over 'model'
    k0 = tp_state.params["dense_0"]["kernel"]
    assert k0.sharding.spec == P(None, "model")
    mu0 = tp_state.opt_state[0].mu["dense_0"]["kernel"]
    assert mu0.sharding.spec == P(None, "model")

    np.testing.assert_allclose(
        float(tp_metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(tp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert int(tp_state.step) == len(batches)


def test_trainer_tp_matches_single_device(eight_devices, tmp_path):
    """Config-driven TP (RunConfig.tp): a dp=2 x tp=4 Trainer reproduces the
    single-device parameter trajectory (same seed => same math under GSPMD)
    and its checkpoint restores into a single-device trainer."""
    import numpy as np

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="mlp", model_kwargs={"hidden": (128, 128), "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=1024, n_test=256,
        batch_size=128, epochs=2, lr=2e-3, quiet=True, seed=3, eval_batch_size=256,
        checkpoint_dir=str(tmp_path / "tp_ck"),
    )
    t_tp = Trainer(RunConfig(name="tp", dp=2, tp=4, **base))
    s_tp = t_tp.fit()  # saves at exit
    t_1 = Trainer(RunConfig(name="one", dp=1, **{**base, "checkpoint_dir": None}))
    t_1.fit()

    a, b = jax.device_get((t_tp.state.params, t_1.state.params))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=5e-4)
    assert np.isfinite(s_tp["best_test_accuracy"])

    # TP checkpoint -> single-device resume (cross-layout, SURVEY.md §5)
    t_r = Trainer(RunConfig(name="r", dp=1, **base))
    restored = t_r.restore_checkpoint()
    assert restored == 2 * t_tp.steps_per_epoch
    for x, y in zip(jax.tree.leaves(jax.device_get(t_tp.state.params)),
                    jax.tree.leaves(jax.device_get(t_r.state.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_trainer_tp_rejects_stream_mode(eight_devices):
    import pytest

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="stream.*tp"):
        Trainer(RunConfig(model="mlp", synthetic=True, n_train=256, n_test=64,
                          batch_size=32, tp=2, input_mode="stream", quiet=True))

"""Ulysses all-to-all sequence parallelism vs dense attention ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import vanilla_attention
from distributed_tensorflow_ibm_mnist_tpu.parallel.sequence_parallel import (
    make_ulysses_attention,
)


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(eight_devices, causal):
    mesh = make_mesh(dp=2, sp=4)
    q, k, v = _qkv()
    attn = make_ulysses_attention(mesh, causal=causal)
    out = jax.jit(attn)(q, k, v)
    ref = vanilla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_matches_ring(eight_devices):
    """The two SP strategies agree with each other (and hence the dense path)."""
    from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import (
        make_ring_attention,
    )

    mesh = make_mesh(dp=1, sp=8)
    q, k, v = _qkv(b=1, s=64, h=8, d=4, seed=1)
    ring = jax.jit(make_ring_attention(mesh, causal=True))(q, k, v)
    uly = jax.jit(make_ulysses_attention(mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=2e-5)


def test_ulysses_fallback_on_indivisible(eight_devices):
    mesh = make_mesh(dp=2, sp=4)
    # heads=2 not divisible by sp=4 -> dense fallback, still correct
    q, k, v = _qkv(h=2)
    out = make_ulysses_attention(mesh)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vanilla_attention(q, k, v)), atol=2e-5
    )


def test_ulysses_custom_inner_attn(eight_devices):
    """inner_attn sees full-sequence, head-sharded blocks."""
    mesh = make_mesh(dp=1, sp=4)
    seen = {}

    def probe(q, k, v, causal=False):
        seen["shape"] = q.shape
        return vanilla_attention(q, k, v, causal=causal)

    q, k, v = _qkv(b=2, s=32, h=4, d=8)
    out = make_ulysses_attention(mesh, inner_attn=probe)(q, k, v)
    assert seen["shape"] == (2, 32, 1, 8)  # full S=32, H/n = 4/4 = 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vanilla_attention(q, k, v)), atol=2e-5
    )


def test_ulysses_in_vit(eight_devices):
    """Drops into the model zoo exactly like ring attention."""
    import optax

    from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    mesh = make_mesh(dp=2, sp=2)
    vit = get_model(
        "vit", patch_size=7, dim=32, depth=2, heads=2,
        attn_fn=make_ulysses_attention(mesh),
    )
    tx = optax.adam(1e-3)
    state = TrainState.create(
        vit, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    step = jax.jit(make_train_step(vit, tx))
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, size=(8, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, size=(8,)).astype(np.int32)),
    }
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))

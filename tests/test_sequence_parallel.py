"""Ulysses all-to-all sequence parallelism vs dense attention ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import vanilla_attention
from distributed_tensorflow_ibm_mnist_tpu.parallel.sequence_parallel import (
    make_ulysses_attention,
)


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(eight_devices, causal):
    mesh = make_mesh(dp=2, sp=4)
    q, k, v = _qkv()
    attn = make_ulysses_attention(mesh, causal=causal)
    out = jax.jit(attn)(q, k, v)
    ref = vanilla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_matches_ring(eight_devices):
    """The two SP strategies agree with each other (and hence the dense path)."""
    from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import (
        make_ring_attention,
    )

    mesh = make_mesh(dp=1, sp=8)
    q, k, v = _qkv(b=1, s=64, h=8, d=4, seed=1)
    ring = jax.jit(make_ring_attention(mesh, causal=True))(q, k, v)
    uly = jax.jit(make_ulysses_attention(mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=2e-5)


def test_ulysses_fallback_on_indivisible(eight_devices):
    mesh = make_mesh(dp=2, sp=4)
    # heads=2 not divisible by sp=4 -> dense fallback, still correct
    q, k, v = _qkv(h=2)
    out = make_ulysses_attention(mesh)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vanilla_attention(q, k, v)), atol=2e-5
    )


def test_ulysses_custom_inner_attn(eight_devices):
    """inner_attn sees full-sequence, head-sharded blocks."""
    mesh = make_mesh(dp=1, sp=4)
    seen = {}

    def probe(q, k, v, causal=False):
        seen["shape"] = q.shape
        return vanilla_attention(q, k, v, causal=causal)

    q, k, v = _qkv(b=2, s=32, h=4, d=8)
    out = make_ulysses_attention(mesh, inner_attn=probe)(q, k, v)
    assert seen["shape"] == (2, 32, 1, 8)  # full S=32, H/n = 4/4 = 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vanilla_attention(q, k, v)), atol=2e-5
    )


def test_ulysses_in_vit(eight_devices):
    """Drops into the model zoo exactly like ring attention."""
    import optax

    from distributed_tensorflow_ibm_mnist_tpu.core import TrainState, make_train_step
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    mesh = make_mesh(dp=2, sp=2)
    vit = get_model(
        "vit", patch_size=7, dim=32, depth=2, heads=2,
        attn_fn=make_ulysses_attention(mesh),
    )
    tx = optax.adam(1e-3)
    state = TrainState.create(
        vit, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    step = jax.jit(make_train_step(vit, tx))
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, size=(8, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, size=(8,)).astype(np.int32)),
    }
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_trainer_sp_impl_ulysses(eight_devices):
    """Config-driven Ulysses (RunConfig.sp_impl) trains a ViT and matches the
    ring-SP trainer's trajectory (both equal the dense math)."""
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="vit",
        model_kwargs={"patch_size": 7, "dim": 16, "depth": 1, "heads": 4,
                      "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=256, n_test=64,
        batch_size=64, epochs=1, lr=1e-3, dp=2, sp=4, quiet=True, seed=5,
        eval_batch_size=64,
    )
    t_uly = Trainer(RunConfig(name="uly", sp_impl="ulysses", **base))
    t_uly.fit()
    t_ring = Trainer(RunConfig(name="ring", sp_impl="ring", **base))
    t_ring.fit()
    a, b = jax.device_get((t_uly.state.params, t_ring.state.params))
    # 1e-3 admits float32 reduction-order drift (all_to_all vs ring partial
    # sums) compounded by adam's rsqrt over an epoch of steps
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-3)


def test_trainer_sp_impl_unknown_raises(eight_devices):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="sp_impl"):
        Trainer(RunConfig(model="vit", synthetic=True, n_train=64, n_test=32,
                          batch_size=32, sp=2, sp_impl="bogus", quiet=True))


def test_trainer_causal_plumbed(eight_devices):
    """RunConfig.causal reaches the attention island: a causal sp=2 run and a
    causal single-device run agree; causal vs non-causal differ."""
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="vit",
        model_kwargs={"patch_size": 7, "dim": 16, "depth": 1, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=128, n_test=32,
        batch_size=32, epochs=1, lr=1e-3, quiet=True, seed=6, eval_batch_size=32,
    )
    t_sp = Trainer(RunConfig(name="sp_causal", dp=1, sp=2, causal=True, **base))
    t_sp.fit()
    t_1 = Trainer(RunConfig(name="one_causal", dp=1, causal=True, **base))
    t_1.fit()
    t_nc = Trainer(RunConfig(name="one_dense", dp=1, causal=False, **base))
    t_nc.fit()

    a, b, c = jax.device_get((t_sp.state.params, t_1.state.params, t_nc.state.params))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=5e-4)
    qkv_causal = a["block_0"]["qkv"]["kernel"]
    qkv_dense = c["block_0"]["qkv"]["kernel"]
    assert np.abs(np.asarray(qkv_causal) - np.asarray(qkv_dense)).max() > 1e-6

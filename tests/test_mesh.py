"""Mesh construction: axis layout + topology-aware device placement."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_ibm_mnist_tpu.parallel import mesh as mesh_mod
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh


def test_mesh_axes_and_sizes(eight_devices):
    m = make_mesh(dp=2, tp=2, sp=2)
    assert m.axis_names == ("data", "model", "seq", "pipe")
    assert m.shape["data"] == 2 and m.shape["model"] == 2 and m.shape["seq"] == 2
    assert m.shape["pipe"] == 1


def test_mesh_dp_fills_remaining(eight_devices):
    m = make_mesh(tp=2)
    assert m.shape["data"] == 4


def test_mesh_oversubscription_raises(eight_devices):
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(dp=4, tp=4)


def test_cpu_mesh_is_list_order(eight_devices):
    """Virtual CPU devices have no topology; placement must stay list-order
    (create_device_mesh would reject them anyway)."""
    m = make_mesh(dp=8)
    assert list(m.devices.flat) == eight_devices[:8]


def test_tpu_path_routes_through_create_device_mesh(monkeypatch):
    """On real TPU devices make_mesh must delegate to
    jax.experimental.mesh_utils.create_device_mesh (VERDICT.md round-1
    item 7: list-order reshape ignores the physical torus)."""

    class FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i
            self.coords = (i, 0, 0)

        def __repr__(self):
            return f"FakeTpu({self.id})"

    fakes = [FakeTpu(i) for i in range(8)]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: fakes)
    called = {}

    from jax.experimental import mesh_utils

    def fake_create(shape, devices=None):
        called["shape"] = tuple(shape)
        called["devices"] = list(devices)
        return np.array(devices, dtype=object).reshape(shape)

    monkeypatch.setattr(mesh_utils, "create_device_mesh", fake_create)
    grid = mesh_mod._device_grid((2, 2, 2, 1), fakes)
    assert called["shape"] == (2, 2, 2, 1)
    assert called["devices"] == fakes
    assert grid.shape == (2, 2, 2, 1)


def test_tpu_subset_falls_back_to_list_order(monkeypatch):
    """Using fewer devices than visible skips create_device_mesh (it requires
    the full slice) and keeps the plain reshape."""

    class FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i
            self.coords = (i, 0, 0)

    fakes = [FakeTpu(i) for i in range(8)]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: fakes)
    grid = mesh_mod._device_grid((4, 1, 1, 1), fakes[:4])
    assert [d.id for d in grid.flat] == [0, 1, 2, 3]

"""Mesh construction: axis layout + topology-aware device placement."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_ibm_mnist_tpu.parallel import mesh as mesh_mod
from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh


pytestmark = pytest.mark.quick  # core numerics: part of the -m quick signal loop


def test_mesh_axes_and_sizes(eight_devices):
    m = make_mesh(dp=2, tp=2, sp=2)
    assert m.axis_names == ("data", "model", "seq", "pipe")
    assert m.shape["data"] == 2 and m.shape["model"] == 2 and m.shape["seq"] == 2
    assert m.shape["pipe"] == 1


def test_mesh_dp_fills_remaining(eight_devices):
    m = make_mesh(tp=2)
    assert m.shape["data"] == 4


def test_mesh_oversubscription_raises(eight_devices):
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(dp=4, tp=4)


def test_cpu_mesh_is_list_order(eight_devices):
    """Virtual CPU devices have no topology; placement must stay list-order
    (create_device_mesh would reject them anyway)."""
    m = make_mesh(dp=8)
    assert list(m.devices.flat) == eight_devices[:8]


def test_tpu_path_routes_through_create_device_mesh(monkeypatch):
    """On real TPU devices make_mesh must delegate to
    jax.experimental.mesh_utils.create_device_mesh (VERDICT.md round-1
    item 7: list-order reshape ignores the physical torus)."""

    class FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i
            self.coords = (i, 0, 0)

        def __repr__(self):
            return f"FakeTpu({self.id})"

    fakes = [FakeTpu(i) for i in range(8)]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: fakes)
    called = {}

    from jax.experimental import mesh_utils

    def fake_create(shape, devices=None):
        called["shape"] = tuple(shape)
        called["devices"] = list(devices)
        return np.array(devices, dtype=object).reshape(shape)

    monkeypatch.setattr(mesh_utils, "create_device_mesh", fake_create)
    grid = mesh_mod._device_grid((2, 2, 2, 1), fakes)
    assert called["shape"] == (2, 2, 2, 1)
    assert called["devices"] == fakes
    assert grid.shape == (2, 2, 2, 1)


def test_tpu_subset_falls_back_to_list_order(monkeypatch):
    """Using fewer devices than visible skips create_device_mesh (it requires
    the full slice) and keeps the plain reshape."""

    class FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i
            self.coords = (i, 0, 0)

    fakes = [FakeTpu(i) for i in range(8)]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: fakes)
    grid = mesh_mod._device_grid((4, 1, 1, 1), fakes[:4])
    assert [d.id for d in grid.flat] == [0, 1, 2, 3]


def test_hybrid_mesh_shapes():
    """Multislice factoring: only the data axis crosses DCN."""
    from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import hybrid_mesh_shapes

    assert hybrid_mesh_shapes(8, 2, 1, 1, dcn_dp=2) == ((4, 2, 1, 1), (2, 1, 1, 1))
    assert hybrid_mesh_shapes(4, 1, 1, 1, dcn_dp=4) == ((1, 1, 1, 1), (4, 1, 1, 1))
    with pytest.raises(ValueError, match="divide"):
        hybrid_mesh_shapes(6, 1, 1, 1, dcn_dp=4)
    with pytest.raises(ValueError, match=">= 1"):
        hybrid_mesh_shapes(4, 1, 1, 1, dcn_dp=0)


def test_dcn_dp_refused_without_multislice_devices(eight_devices):
    """Virtual CPU devices carry no slice_index: dcn_dp>1 must refuse with
    a clear error instead of silently building a flat mesh."""
    with pytest.raises(ValueError, match="slice"):
        make_mesh(dp=8, dcn_dp=2)


def test_config_dcn_dp_plumbs_to_mesh(eight_devices):
    """RunConfig.dcn_dp reaches make_mesh (and fails loudly here, where no
    multislice runtime exists) — even at dp=1, where the mesh build is
    otherwise skipped."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        model="mlp", model_kwargs={"hidden": (32,)}, synthetic=True,
        n_train=64, n_test=32, batch_size=32, epochs=1, quiet=True,
        dp=8, dcn_dp=2,
    )
    with pytest.raises(ValueError, match="slice"):
        Trainer(cfg)
    # dp=1 must not silently ignore the multislice request...
    with pytest.raises(ValueError, match="divide"):
        Trainer(cfg.replace(dp=1))
    # ...and invalid values are refused, not clamped
    with pytest.raises(ValueError, match=">= 1"):
        Trainer(cfg.replace(dcn_dp=0))


class _SliceDev:
    """A real (virtual CPU) device dressed with a slice_index — enough for
    the multislice selection AND create_hybrid_device_mesh to run in CI."""

    def __init__(self, dev, slice_index):
        self._dev = dev
        self.slice_index = slice_index

    def __getattr__(self, name):
        return getattr(self._dev, name)

    def __repr__(self):
        return f"SliceDev({self._dev.id}, slice={self.slice_index})"


def test_pick_multislice_devices_groups_per_slice(eight_devices):
    """The positive multislice branch EXECUTES (VERDICT.md r3 item 6): the
    selection takes per_slice devices from each slice — never a flat
    prefix — ignores sliceless devices, and keeps slices contiguous."""
    from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import (
        pick_multislice_devices,
    )

    devs = list(eight_devices)
    # interleave slice membership so a flat prefix would be WRONG: slices
    # 0/1 alternate, plus two devices with no slice at the front
    mocked = [_SliceDev(d, i % 2) for i, d in enumerate(devs[2:])] + devs[:2]
    chosen = pick_multislice_devices(mocked, dcn_dp=2, per_slice=3)
    assert [c.slice_index for c in chosen] == [0, 0, 0, 1, 1, 1]
    assert len({c.id for c in chosen}) == 6
    # slice 0 got the even-indexed tail devices, slice 1 the odd ones
    assert [c.id for c in chosen[:3]] == [d.id for d in devs[2::2]]
    assert [c.id for c in chosen[3:]] == [d.id for d in devs[3::2]]

    # not enough slices -> the documented refusal, naming what it found
    with pytest.raises(ValueError, match="slice indices \\[0, 1\\]"):
        pick_multislice_devices(mocked, dcn_dp=3, per_slice=2)
    # enough slices but too few devices per slice
    with pytest.raises(ValueError, match="slice"):
        pick_multislice_devices(mocked, dcn_dp=2, per_slice=4)


def test_make_mesh_multislice_positive_branch(eight_devices):
    """make_mesh(dcn_dp=2) end to end on mock two-slice devices: the
    hybrid mesh comes back (2 slices x 4 chips) with the data axis — and
    ONLY the data axis — crossing slices."""
    devs = [_SliceDev(d, i // 4) for i, d in enumerate(eight_devices)]
    mesh = make_mesh(dp=4, tp=2, dcn_dp=2, devices=devs)
    assert mesh.axis_names == ("data", "model", "seq", "pipe")
    assert mesh.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    grid = mesh.devices  # (4, 2, 1, 1)
    # the data axis factors (dcn x within-slice): rows 0-1 slice 0, rows
    # 2-3 slice 1 — crossing the data axis crosses slices at one boundary
    for m in range(2):
        assert {grid[i, m, 0, 0].slice_index for i in range(2)} == {0}
        assert {grid[i, m, 0, 0].slice_index for i in range(2, 4)} == {1}
        # model-axis neighbors NEVER cross slices
        for i in range(4):
            assert grid[i, 0, 0, 0].slice_index == grid[i, 1, 0, 0].slice_index
